"""The batched constraint×resource match kernel.

One jitted call computes the full [C, N] boolean match matrix that the
reference evaluates as C×N interpreted Rego queries over
`matching_constraints` (pkg/target/target_template_source.go:27-44). All
operands are small-int comparisons and masked reductions — pure VPU work
that XLA fuses into a handful of elementwise kernels; there is no gather
into host vocab and no string touch on device.

Shape conventions: constraint tensors are [C, ...], review features [N, ...],
everything broadcasts to [C, N]. Padded slots are -1 and excluded by
validity masks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .matchspec import (
    MatchSpecSet,
    OP_ALWAYS_VIOLATED,
    OP_EXISTS,
    OP_IN,
    OP_NOT_EXISTS,
    OP_NOT_IN,
    SCOPE_ABSENT,
    SCOPE_CLUSTER,
    SCOPE_NAMESPACED,
    SCOPE_STAR,
    WILDCARD,
)


def _isin(needle, haystack):
    """needle [..., 1] in haystack [..., M] (-1 pads never match)."""
    return jnp.any(
        (haystack != -1) & (haystack == needle[..., None]), axis=-1
    )


def _selector_match(invalid, ml, expr, expr_vals, labels):
    """LabelSelector vs label pairs.

    invalid [C], ml [C,P,2], expr [C,E,3], expr_vals [C,E,V],
    labels [N,ML,2]  ->  [C,N] bool.
    """
    lab_k = labels[None, :, :, 0]  # [1, N, ML]
    lab_v = labels[None, :, :, 1]

    # matchLabels: every declared pair present & equal
    ml_k = ml[:, None, :, 0]  # [C, 1, P]
    ml_v = ml[:, None, :, 1]
    pair_valid = ml_k != -1
    # [C, N, P, ML]: label j satisfies pair p
    hit = (lab_k[:, :, None, :] == ml_k[..., None]) & (
        lab_v[:, :, None, :] == ml_v[..., None]
    )
    pair_ok = jnp.any(hit, axis=-1)  # [C, N, P]
    ml_ok = jnp.all(~pair_valid | pair_ok, axis=-1)  # [C, N]

    # matchExpressions
    e_key = expr[:, None, :, 0]  # [C, 1, E]
    e_op = expr[:, None, :, 1]
    e_nv = expr[:, None, :, 2]
    key_hit = lab_k[:, :, None, :] == e_key[..., None]  # [C, N, E, ML]
    has_key = jnp.any(key_hit, axis=-1)  # [C, N, E]
    # value of the matching label (keys unique per object)
    label_val = jnp.max(
        jnp.where(key_hit, lab_v[:, :, None, :], -1), axis=-1
    )  # [C, N, E]
    in_vals = _isin(label_val, expr_vals[:, None, :, :])  # [C, N, E]

    violated = jnp.zeros_like(has_key, dtype=bool)
    violated = jnp.where(
        e_op == OP_IN, ~has_key | ((e_nv > 0) & ~in_vals), violated
    )
    violated = jnp.where(
        e_op == OP_NOT_IN, has_key & (e_nv > 0) & in_vals, violated
    )
    violated = jnp.where(e_op == OP_EXISTS, ~has_key, violated)
    violated = jnp.where(e_op == OP_NOT_EXISTS, has_key, violated)
    violated = jnp.where(e_op == OP_ALWAYS_VIOLATED, True, violated)
    any_violated = jnp.any(violated, axis=-1)  # [C, N]

    return ml_ok & ~any_violated & ~invalid[:, None]


def _labelselector_4case(invalid, ml, expr, expr_vals, fb):
    """any_labelselector_match (target_template_source.go:233-281): OR over
    object/oldObject labels according to which of the two is present."""
    m_obj = _selector_match(invalid, ml, expr, expr_vals, fb["obj_labels"])
    m_old = _selector_match(invalid, ml, expr, expr_vals, fb["old_labels"])
    obj_p = fb["obj_present"][None, :]
    old_p = fb["old_present"][None, :]
    both = m_obj | m_old
    # obj&old -> OR; only old -> old; only obj or neither -> obj (neither:
    # obj_labels is all-pad == empty labels, exactly the 4th clause)
    return jnp.where(
        obj_p & old_p, both, jnp.where(old_p & ~obj_p, m_old, m_obj)
    )


@partial(jax.jit, static_argnames=())
def match_matrix(ms: dict, fb: dict) -> jnp.ndarray:
    """[C, N] bool — matching_constraints for every (constraint, review).

    `ms`/`fb` are dicts of jnp arrays (MatchSpecSet / FeatureBatch fields);
    passing dicts keeps the jit cache keyed purely on shapes.
    """
    # kind selector (:131-156)
    rows = ms["kind_rows"]  # [C, K, 2]
    g = rows[:, None, :, 0]  # [C, 1, K]
    k = rows[:, None, :, 1]
    rg = fb["group_id"][None, :, None]  # [1, N, 1]
    rk = fb["kind_id"][None, :, None]
    row_valid = (g != -1) & (g > -3) | (g == WILDCARD)
    g_ok = (g == WILDCARD) | ((rg >= 0) & (g == rg))
    k_ok = (k == WILDCARD) | ((rk >= 0) & (k == rk))
    kind_ok = jnp.any(row_valid & g_ok & k_ok, axis=-1)  # [C, N]

    # always_match_ns_selectors (:311-314): `not is_ns(input.review.kind)`
    # has its operand hoisted, so an undefined kind fails the clause
    always = (
        fb["kind_defined"] & ~fb["is_ns"] & ~fb["has_namespace"]
    )[None, :]  # [1, N]
    ns_name = fb["ns_name_id"]  # [N]
    ns_defined = (ns_name >= 0)[None, :]

    # namespaces (:316-332)
    in_ns = _isin(ns_name[None, :], ms["ns_ids"][:, None, :])
    ns_ok = ~ms["ns_has"][:, None] | always | (ns_defined & in_ns)

    # excludedNamespaces (:334-350)
    in_excl = _isin(ns_name[None, :], ms["excl_ids"][:, None, :])
    excl_ok = ~ms["excl_has"][:, None] | always | (ns_defined & ~in_excl)

    # scope (:162-178)
    sc = ms["scope"][:, None]
    has_ns = fb["has_namespace"][None, :]
    scope_ok = (
        (sc == SCOPE_ABSENT)
        | (sc == SCOPE_STAR)
        | ((sc == SCOPE_NAMESPACED) & has_ns)
        | ((sc == SCOPE_CLUSTER) & ~has_ns)
    )

    # namespaceSelector (:352-386)
    nssel_plain = _selector_match(
        ms["nssel_invalid"],
        ms["nssel_ml"],
        ms["nssel_expr"],
        ms["nssel_expr_vals"],
        fb["nssel_labels"],
    )
    nssel_self = _labelselector_4case(
        ms["nssel_invalid"],
        ms["nssel_ml"],
        ms["nssel_expr"],
        ms["nssel_expr_vals"],
        fb,
    )
    is_ns = fb["is_ns"][None, :]
    # second get_ns candidate with empty labels (partial-set semantics):
    # selector-vs-empty is constraint-static, computed host-side
    nssel_with_empty = nssel_plain | (
        fb["nssel_empty"][None, :] & ms["nssel_matches_empty"][:, None]
    )
    nssel_eval = jnp.where(
        is_ns, nssel_self, fb["nssel_defined"][None, :] & nssel_with_empty
    )
    nssel_ok = ~ms["nssel_has"][:, None] | always | nssel_eval

    # labelSelector (:233-281)
    label_ok = _labelselector_4case(
        ms["lab_invalid"], ms["lab_ml"], ms["lab_expr"], ms["lab_expr_vals"], fb
    )

    return kind_ok & ns_ok & excl_ok & scope_ok & nssel_ok & label_ok


def matchspec_to_np(ms: MatchSpecSet) -> dict:
    """MatchSpecSet -> plain dict of numpy arrays (the kernel's input
    keys); callers shard/pad/ship to device as they see fit."""
    import numpy as np

    return {
        "kind_rows": np.asarray(ms.kind_rows),
        "ns_has": np.asarray(ms.ns_has),
        "ns_ids": np.asarray(ms.ns_ids),
        "excl_has": np.asarray(ms.excl_has),
        "excl_ids": np.asarray(ms.excl_ids),
        "scope": np.asarray(ms.scope),
        "lab_invalid": np.asarray(ms.lab_invalid),
        "lab_ml": np.asarray(ms.lab_ml),
        "lab_expr": np.asarray(ms.lab_expr),
        "lab_expr_vals": np.asarray(ms.lab_expr_vals),
        "nssel_has": np.asarray(ms.nssel_has),
        "nssel_matches_empty": np.asarray(ms.nssel_matches_empty),
        "nssel_invalid": np.asarray(ms.nssel_invalid),
        "nssel_ml": np.asarray(ms.nssel_ml),
        "nssel_expr": np.asarray(ms.nssel_expr),
        "nssel_expr_vals": np.asarray(ms.nssel_expr_vals),
    }


def matchspec_to_device(ms: MatchSpecSet) -> dict:
    return {k: jnp.asarray(v) for k, v in matchspec_to_np(ms).items()}


def features_to_device(fb) -> dict:
    return {
        "group_id": jnp.asarray(fb.group_id),
        "kind_id": jnp.asarray(fb.kind_id),
        "kind_defined": jnp.asarray(fb.kind_defined),
        "is_ns": jnp.asarray(fb.is_ns),
        "has_namespace": jnp.asarray(fb.has_namespace),
        "ns_name_id": jnp.asarray(fb.ns_name_id),
        "obj_present": jnp.asarray(fb.obj_present),
        "old_present": jnp.asarray(fb.old_present),
        "obj_labels": jnp.asarray(fb.obj_labels),
        "old_labels": jnp.asarray(fb.old_labels),
        "nssel_defined": jnp.asarray(fb.nssel_defined),
        "nssel_labels": jnp.asarray(fb.nssel_labels),
        "nssel_empty": jnp.asarray(fb.nssel_empty),
    }
