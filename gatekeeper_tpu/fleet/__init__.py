"""Fleet plane: the subsystem that turns per-process state into fleet
state, unblocking multi-replica HA webhook serving (docs/fleet.md,
ROADMAP item 2).

Three legs, one seam (`control.events.EventSource`, so everything runs
identically against the FakeCluster and a live apiserver):

  * `SecretCertStore` + `FleetCertRotator` — the Secret-backed shared
    cert store: load-or-create with conflict retry (losers adopt the
    winner's CA), peers pick rotation up from the watch WITHOUT restart
    (pkg/webhook/certs.go:119-181 behaviorally);
  * `FleetPlane` — CR-backed gossip for the external-data response
    cache (N replicas stop paying N× cold fetches) and circuit-breaker
    trips (an outage one replica discovered pre-opens peers to a
    half-open probe).
"""

from .certs import FleetCertRotator
from .plane import FLEETSTATE_GVK, FleetPlane
from .store import (
    CertRecord,
    DEFAULT_SECRET_NAME,
    GENERATION_ANNOTATION,
    SECRET_GVK,
    SecretCertStore,
)

__all__ = [
    "CertRecord",
    "DEFAULT_SECRET_NAME",
    "FLEETSTATE_GVK",
    "FleetCertRotator",
    "FleetPlane",
    "GENERATION_ANNOTATION",
    "SECRET_GVK",
    "SecretCertStore",
]
