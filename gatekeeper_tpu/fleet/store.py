"""Secret-backed shared cert store: one CA per fleet, not per pod.

The reference keeps the webhook's CA + server pair in a Secret that
every replica mounts, and resolves boot races with a load-or-create +
conflict-retry loop (pkg/webhook/certs.go:119-181): whoever creates the
Secret first wins; losers re-read and serve the winner's CA. This module
is that store behind the `EventSource` seam, so the same code runs
against the FakeCluster (tests, two in-process replicas) and a live
apiserver (`KubeCluster.create`/`apply`):

  * `load()` — the current fleet pair, or None;
  * `offer(artifacts, expected_generation)` — try to make a freshly
    generated pair THE fleet pair. Absent → atomic `create()` (a 409
    loser adopts the winner); present → generation-checked replace, so
    two replicas rotating simultaneously converge on one writer and
    the other adopts;
  * `watch(callback)` — rotation events for peers: a replica that
    did not rotate picks the new pair up from the Secret without
    restarting (docs/fleet.md).

Artifacts travel as the ca.crt / tls.crt / tls.key triple, base64 in
`data` exactly like a mounted TLS Secret, plus a monotonically
increasing generation annotation that makes "who rotated, and have I
installed it yet" a pure integer comparison.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from ..control.events import Conflict, DELETED, GVK
from ..logs import null_logger

SECRET_GVK = GVK("", "v1", "Secret")

DEFAULT_SECRET_NAME = "gatekeeper-webhook-server-cert"
DEFAULT_NAMESPACE = "gatekeeper-system"

GENERATION_ANNOTATION = "fleet.gatekeeper.sh/generation"
ROTATED_BY_ANNOTATION = "fleet.gatekeeper.sh/rotated-by"

ARTIFACT_KEYS = ("ca.crt", "tls.crt", "tls.key")


@dataclass(frozen=True)
class CertRecord:
    """One parsed store state: the PEM triple + rotation provenance."""

    artifacts: Dict[str, bytes]
    generation: int
    rotated_by: str


class SecretCertStore:
    def __init__(
        self,
        cluster,
        name: str = DEFAULT_SECRET_NAME,
        namespace: str = DEFAULT_NAMESPACE,
        replica_id: str = "",
        metrics=None,
        logger=None,
    ):
        self.cluster = cluster
        self.name = name
        self.namespace = namespace
        self.replica_id = replica_id
        self.metrics = metrics
        self.log = logger if logger is not None else null_logger()
        self.conflicts = 0  # create/rotate races lost (tests/readyz)

    # -- (de)serialization ----------------------------------------------------

    def _secret_obj(self, artifacts: Dict[str, bytes],
                    generation: int) -> Dict:
        return {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "annotations": {
                    GENERATION_ANNOTATION: str(generation),
                    ROTATED_BY_ANNOTATION: self.replica_id,
                },
            },
            "type": "Opaque",
            "data": {
                k: base64.b64encode(artifacts[k]).decode()
                for k in ARTIFACT_KEYS
            },
        }

    @staticmethod
    def parse(obj: Optional[Dict]) -> Optional[CertRecord]:
        """Secret object -> CertRecord, or None when the object is
        missing or holds an incomplete triple (a placeholder Secret the
        chart ships empty parses as None → first boot generates)."""
        if obj is None:
            return None
        data = obj.get("data") or {}
        artifacts: Dict[str, bytes] = {}
        for k in ARTIFACT_KEYS:
            raw = data.get(k)
            if not raw:
                return None
            try:
                artifacts[k] = base64.b64decode(raw)
            except Exception:
                return None
        meta = obj.get("metadata") or {}
        ann = meta.get("annotations") or {}
        try:
            generation = int(ann.get(GENERATION_ANNOTATION, "1"))
        except ValueError:
            generation = 1
        return CertRecord(
            artifacts=artifacts,
            generation=generation,
            rotated_by=str(ann.get(ROTATED_BY_ANNOTATION, "")),
        )

    # -- reads ----------------------------------------------------------------

    def _get_obj(self) -> Optional[Dict]:
        getter = getattr(self.cluster, "get", None)
        if getter is not None:
            return getter(SECRET_GVK, self.namespace, self.name)
        for obj in self.cluster.list(SECRET_GVK):
            meta = obj.get("metadata") or {}
            if (meta.get("namespace"), meta.get("name")) == (
                self.namespace,
                self.name,
            ):
                return obj
        return None

    def load(self) -> Optional[CertRecord]:
        return self.parse(self._get_obj())

    # -- the load-or-create / rotate write ------------------------------------

    def offer(
        self, artifacts: Dict[str, bytes], expected_generation: int = 0
    ) -> Tuple[CertRecord, bool]:
        """Try to make `artifacts` the fleet pair; returns
        (winning record, we_won). `expected_generation` is the store
        generation the caller based its decision on: 0 = it saw no
        usable Secret (load-or-create), N = it decided generation N is
        due for rotation. Every losing path re-reads and returns the
        WINNER's record — the caller must serve that, never its own
        candidate (certs.go:119-181)."""
        mine = CertRecord(
            artifacts=dict(artifacts),
            generation=expected_generation + 1,
            rotated_by=self.replica_id,
        )
        obj = self._secret_obj(artifacts, mine.generation)
        if expected_generation == 0:
            create = getattr(self.cluster, "create", None)
            existing = self._get_obj()
            if existing is not None:
                winner = self.parse(existing)
                if winner is not None:
                    # usable pair appeared between the caller's load and
                    # this offer: adopt it, write nothing
                    return self._lost_race("create", winner), False
            elif create is not None:
                try:
                    create(obj)
                    return mine, True
                except Conflict:
                    winner = self.load()
                    if winner is not None:
                        return self._lost_race("create", winner), False
                    # the race winner wrote something UNUSABLE (or the
                    # chart's empty placeholder landed between our get
                    # and create): replace it below
            # an existing-but-unusable Secret (the chart ships an empty
            # placeholder) — or a seam without create(): replace, then
            # re-read to detect a same-window double replace
            self.cluster.apply(obj)
            after = self.load()
            if after is not None and (
                (after.generation, after.rotated_by)
                != (mine.generation, self.replica_id)
            ):
                return self._lost_race("create", after), False
            return mine, True
        # rotation: a generation-checked replace. The re-read-then-apply
        # window is narrow but real; the generation check plus the final
        # re-read below make a double rotation converge on one winner.
        cur = self.load()
        if cur is not None and cur.generation != expected_generation:
            return self._lost_race("rotate", cur), False
        self.cluster.apply(obj)
        after = self.load()
        if (
            after is not None
            and (after.generation, after.rotated_by)
            != (mine.generation, self.replica_id)
        ):
            return self._lost_race("rotate", after), False
        return mine, True

    def _lost_race(
        self, kind: str, winner: Optional[CertRecord] = None
    ) -> CertRecord:
        self.conflicts += 1
        if self.metrics is not None:
            self.metrics.record("fleet_cert_conflicts_total", 1, kind=kind)
        self.log.info(
            "cert store conflict: adopting the winner's pair",
            process="fleet", kind=kind, replica=self.replica_id,
        )
        if winner is None:
            winner = self.load()
        if winner is None:
            # created-then-deleted under our feet: surface it — the
            # caller's next ensure() recreates from scratch
            raise Conflict(
                f"cert secret {self.namespace}/{self.name} vanished "
                "while resolving a write conflict"
            )
        return winner

    # -- watch ----------------------------------------------------------------

    def watch(
        self, callback: Callable[[Optional[CertRecord]], None]
    ) -> Callable[[], None]:
        """Subscribe to the Secret; `callback(record)` fires on every
        ADDED/MODIFIED of OUR secret (None on DELETED). This is how a
        replica that did not rotate picks up a peer's rotation without
        restart."""

        def sink(ev):
            meta = ev.obj.get("metadata") or {}
            if (meta.get("namespace"), meta.get("name")) != (
                self.namespace,
                self.name,
            ):
                return
            if ev.type == DELETED:
                callback(None)
                return
            rec = self.parse(ev.obj)
            if rec is not None:
                callback(rec)

        return self.cluster.subscribe(SECRET_GVK, sink)
