"""FleetPlane: CR-backed gossip turning per-process caches and breakers
into fleet state.

Each replica owns ONE `FleetState` CR (named by its replica id) and
watches the whole kind through the `EventSource` seam. Outbound: a
debounced publisher thread serializes the replica's shareable state —
fresh local-origin external-data cache entries (`ResponseCache.
export_fresh`) and the current state of every registered circuit
breaker — and `apply()`s it. Inbound: peers' CR writes arrive as watch
events and merge:

  * cache entries adopt iff fresher than what we hold, with relative
    ages so TTL / negative / stale-while-revalidate windows survive the
    clock hop (`ResponseCache.merge`); adopted entries carry the peer's
    id as origin and are never re-published from here (no echo loops);
  * breaker states adopt via `CircuitBreaker.adopt`: a peer's OPEN
    pre-opens the local breaker to HALF_OPEN — the next batch is a
    single probe instead of `failure_threshold` full batches
    rediscovering an outage the fleet already paid for; a peer's
    CLOSED lets an OPEN local breaker probe early.

Everything is best-effort: a publish failure is counted and retried on
the next dirty wake (serving never blocks on the state plane), and a
cluster without the FleetState CRD degrades to exactly the old
per-process behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from ..control.events import DELETED, GVK
from ..logs import null_logger

FLEET_GROUP = "fleet.gatekeeper.sh"
FLEET_VERSION = "v1alpha1"
FLEETSTATE_GVK = GVK(FLEET_GROUP, FLEET_VERSION, "FleetState")

DEFAULT_NAMESPACE = "gatekeeper-system"


class FleetPlane:
    def __init__(
        self,
        cluster,
        replica_id: str,
        namespace: str = DEFAULT_NAMESPACE,
        metrics=None,
        logger=None,
        publish_interval_s: float = 0.25,
        max_published_entries: int = 512,
    ):
        self.cluster = cluster
        self.replica_id = replica_id
        self.namespace = namespace
        self.metrics = metrics
        self.log = logger if logger is not None else null_logger()
        self.publish_interval_s = publish_interval_s
        self.max_published_entries = max_published_entries
        self._lock = threading.Lock()
        self._breakers: Dict[str, Any] = {}
        self._peers: Set[str] = set()
        self._cache_system = None
        self.cache_merged = 0
        self.breaker_adoptions = 0
        self.publishes = 0
        self.publish_failures = 0
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unsubscribe: Optional[Callable[[], None]] = None
        self.started = False

    # -- attachment ------------------------------------------------------------

    def attach_cache(self, system) -> None:
        """Wire an ExternalDataSystem: its cache entries publish, peers'
        merge in, and its per-provider breakers gossip (the system calls
        register_breaker as providers upsert)."""
        self._cache_system = system
        set_fleet = getattr(system, "set_fleet", None)
        if set_fleet is not None:
            set_fleet(self)

    def register_breaker(self, name: str, breaker) -> None:
        """Track a breaker under a fleet-wide name (`device:validation`,
        `provider:<name>`, ...). Its transitions mark the plane dirty so
        trips reach peers within one publish interval."""
        with self._lock:
            if self._breakers.get(name) is breaker:
                return
            self._breakers[name] = breaker
        subscribe = getattr(breaker, "subscribe", None)
        if subscribe is not None:
            subscribe(lambda _f, _t: self._dirty.set())
        self._dirty.set()

    def unregister_breaker(self, name: str) -> None:
        with self._lock:
            self._breakers.pop(name, None)

    def notify_cache_update(self) -> None:
        """Called by the attached cache system after a successful fetch
        populated new entries — wakes the debounced publisher."""
        self._dirty.set()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self.started:
            return
        self.started = True
        self._unsubscribe = self.cluster.subscribe(
            FLEETSTATE_GVK, self._on_event
        )
        # merge whatever peers already published (informer initial List)
        try:
            for obj in self.cluster.list(FLEETSTATE_GVK):
                self._merge_obj(obj)
        except Exception as e:
            self.log.error(
                "fleet state list failed", process="fleet", err=e
            )
        self.publish()
        self._thread = threading.Thread(
            target=self._loop, name="gk-fleet-publisher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.started = False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait()
            if self._stop.is_set():
                return
            self._dirty.clear()
            self.publish()
            # debounce: coalesce bursts of cache fills / breaker churn
            # into one CR write per interval
            self._stop.wait(self.publish_interval_s)

    # -- outbound --------------------------------------------------------------

    def state_obj(self) -> Dict[str, Any]:
        entries: List[Dict[str, Any]] = []
        if self._cache_system is not None:
            entries = self._cache_system.cache.export_fresh(
                self.max_published_entries
            )
        with self._lock:
            breakers = [
                {"name": name, "state": b.state}
                for name, b in sorted(self._breakers.items())
            ]
        return {
            "apiVersion": f"{FLEET_GROUP}/{FLEET_VERSION}",
            "kind": "FleetState",
            "metadata": {
                "name": self.replica_id,
                "namespace": self.namespace,
            },
            "spec": {
                "replica": self.replica_id,
                "cache": entries,
                "breakers": breakers,
            },
        }

    def publish(self) -> bool:
        try:
            self.cluster.apply(self.state_obj())
        except Exception as e:
            with self._lock:
                self.publish_failures += 1
            if self.metrics is not None:
                self.metrics.record("fleet_state_publish_failures_total", 1)
            self.log.debug(
                "fleet state publish failed (degrading to per-process "
                "state)", process="fleet", err=str(e),
            )
            return False
        with self._lock:
            self.publishes += 1
        if self.metrics is not None:
            self.metrics.record("fleet_state_publishes_total", 1)
        return True

    # -- inbound ---------------------------------------------------------------

    def _on_event(self, ev) -> None:
        meta = ev.obj.get("metadata") or {}
        if meta.get("namespace") not in (None, "", self.namespace):
            return
        name = meta.get("name") or ""
        if name == self.replica_id:
            return  # our own write echoing back
        if ev.type == DELETED:
            with self._lock:
                self._peers.discard(name)
            self._report_peers()
            return
        self._merge_obj(ev.obj)

    def _merge_obj(self, obj: Dict[str, Any]) -> None:
        spec = obj.get("spec") or {}
        origin = str(
            spec.get("replica")
            or (obj.get("metadata") or {}).get("name")
            or ""
        )
        if not origin or origin == self.replica_id:
            return
        with self._lock:
            self._peers.add(origin)
        self._report_peers()
        merged = 0
        if self._cache_system is not None:
            for rec in spec.get("cache") or []:
                try:
                    if self._cache_system.cache.merge(rec, origin):
                        merged += 1
                except Exception:
                    continue  # one malformed record must not stop the rest
        if merged:
            with self._lock:
                self.cache_merged += merged
            if self.metrics is not None:
                self.metrics.record(
                    "fleet_cache_merged_total", merged, peer=origin
                )
        for brec in spec.get("breakers") or []:
            name = str(brec.get("name") or "")
            state = str(brec.get("state") or "")
            with self._lock:
                breaker = self._breakers.get(name)
            if breaker is None or not state:
                continue
            adopt = getattr(breaker, "adopt", None)
            if adopt is None:
                continue
            if adopt(state):
                with self._lock:
                    self.breaker_adoptions += 1
                if self.metrics is not None:
                    self.metrics.record(
                        "fleet_breaker_adoptions_total", 1,
                        breaker=name, peer_state=state,
                    )
                self.log.info(
                    "adopted peer breaker state",
                    process="fleet", breaker=name,
                    peer=origin, peer_state=state,
                )

    def _report_peers(self) -> None:
        if self.metrics is not None:
            with self._lock:
                n = len(self._peers)
            self.metrics.gauge("fleet_peers", n)

    # -- introspection ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Readyz/debug view (stats.fleet, docs/fleet.md)."""
        with self._lock:
            return {
                "replica": self.replica_id,
                "peers": sorted(self._peers),
                "cache_merged": self.cache_merged,
                "breaker_adoptions": self.breaker_adoptions,
                "publishes": self.publishes,
                "publish_failures": self.publish_failures,
                "breakers": sorted(self._breakers),
            }
