"""FleetCertRotator: the CertRotator whose source of truth is the
shared Secret, not pod-local disk.

Local files still exist — `ssl.SSLContext.load_cert_chain` wants paths —
but they are a *cache* of the store: every install goes through the
base rotator's write-then-atomic-rename so concurrent `ensure()` callers
and rotation racing a TLS handshake can never observe a torn
ca.crt/tls.crt pair. The lifecycle (certs.go:119-181 behaviorally):

  * `ensure()` — load the Secret; fresh → install (if not already at
    that generation) and serve. Missing/expiring → generate a candidate
    pair and `offer()` it; losing the create/rotate race installs the
    winner's pair instead (one CA per fleet, always);
  * `start()` — watch the Secret: a peer's rotation arrives as a watch
    event, installs atomically, bumps `cert_generation`, and fires the
    `on_rotate` callbacks (the serving layer re-loads its SSL context;
    the CaBundleInjector re-injects the VWH) — rotation propagates to
    every replica WITHOUT restart.
"""

from __future__ import annotations

import datetime
import threading
from typing import Callable, Dict, List, Optional

from ..logs import null_logger
from ..webhook.certs import CertRotator, LOOKAHEAD_DAYS
from .store import CertRecord, SecretCertStore


class FleetCertRotator(CertRotator):
    def __init__(
        self,
        cert_dir: str,
        store: SecretCertStore,
        dns_name: str = "localhost",
        now=None,
        metrics=None,
        logger=None,
    ):
        super().__init__(cert_dir, dns_name=dns_name, now=now)
        # reentrant: watch events delivered synchronously during an
        # offer() land back in _install_record on the same thread
        self._lock = threading.RLock()
        self.store = store
        self.metrics = metrics
        self.log = logger if logger is not None else null_logger()
        self.cert_generation = 0  # store generation currently installed
        # (generation, rotated_by) of the installed pair: generation
        # alone is ambiguous when two replicas rotate in the same
        # window and both write generation N — identity disambiguates
        self._installed_id = (0, "")
        self.rotations_adopted = 0  # peer rotations installed via watch
        self._rotate_callbacks: List[Callable[[], None]] = []
        self._unsubscribe = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Begin watching the Secret for peer rotations."""
        if self._unsubscribe is None:
            self._unsubscribe = self.store.watch(self._on_record)

    def stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def on_rotate(self, callback: Callable[[], None]) -> None:
        """Register a callback fired after ANY new pair is installed
        (own rotation or a peer's): SSL-context reload, CA re-inject."""
        with self._lock:
            self._rotate_callbacks.append(callback)

    # -- the contract ---------------------------------------------------------

    def ensure(self):
        with self._lock:
            rec = self.store.load()
            if rec is not None and not self._record_needs_refresh(rec):
                self._install_record(rec)
                return self.cert_path, self.key_path
            expected = rec.generation if rec is not None else 0
            winner, won = self.store.offer(
                self.generate_pair(), expected_generation=expected
            )
            self._install_record(winner)
            if won:
                self.rotations += 1
        return self.cert_path, self.key_path

    # -- internals ------------------------------------------------------------

    def _record_needs_refresh(self, rec: CertRecord) -> bool:
        exp = self.pem_expiry(rec.artifacts.get("tls.crt", b""))
        if exp is None:
            return True
        lookahead = self._now() + datetime.timedelta(days=LOOKAHEAD_DAYS)
        return exp <= lookahead

    def _install_record(self, rec: CertRecord) -> bool:
        """Install iff `rec` is new: strictly newer generation, or the
        same generation written by a DIFFERENT replica (the store's
        current content after a same-window double rotation — the
        caller only hands us authoritative records). Returns True when
        the pair on disk changed."""
        with self._lock:
            rid = (rec.generation, rec.rotated_by)
            if rid == self._installed_id:
                return False
            if rec.generation < self._installed_id[0]:
                return False  # stale record
            self.install_artifacts(rec.artifacts)
            self._installed_id = rid
            self.cert_generation = rec.generation
            if self.metrics is not None:
                self.metrics.gauge(
                    "fleet_cert_generation", rec.generation
                )
            callbacks = list(self._rotate_callbacks)
        for cb in callbacks:
            try:
                cb()
            except Exception as e:
                self.log.error(
                    "cert rotation callback failed",
                    process="fleet", err=e,
                )
        return True

    def _on_record(self, rec: Optional[CertRecord]) -> None:
        """Watch sink: a peer rotated (or the Secret vanished).

        NON-BLOCKING on the rotator lock: watch events are delivered
        synchronously from the writer's thread (FakeCluster), so two
        replicas inside ensure() writing the store would otherwise
        deadlock AB-BA (each holding its own lock, each delivering into
        the other's sink). If the lock is busy, the holder is inside
        ensure() and will install the store's authoritative record
        itself — we just re-check once it releases, off-thread."""
        if rec is None:
            return  # deletion: the next ensure() recreates
        if not self._lock.acquire(blocking=False):
            threading.Thread(
                target=self._deferred_recheck,
                name="gk-fleet-cert-recheck",
                daemon=True,
            ).start()
            return
        try:
            self._handle_record_locked(rec)
        finally:
            self._lock.release()

    def _deferred_recheck(self) -> None:
        with self._lock:
            rec = self.store.load()
            if rec is not None:
                self._handle_record_locked(rec)

    def _handle_record_locked(self, rec: CertRecord) -> None:
        rid = (rec.generation, rec.rotated_by)
        if (
            rid == self._installed_id
            or rec.generation < self.cert_generation
        ):
            return
        if rec.generation == self.cert_generation:
            # same generation, different writer: a delayed event
            # from a double rotation — the STORE is authoritative,
            # not the event payload (events replay in write order
            # but we may have installed past this one already)
            rec = self.store.load()
            if rec is None or (
                (rec.generation, rec.rotated_by)
                == self._installed_id
            ):
                return
        if (
            self._install_record(rec)
            and rec.rotated_by != self.store.replica_id
        ):
            self.rotations_adopted += 1
            if self.metrics is not None:
                self.metrics.record(
                    "fleet_cert_rotations_adopted_total", 1,
                    rotated_by=rec.rotated_by or "unknown",
                )
            self.log.info(
                "adopted peer cert rotation without restart",
                process="fleet",
                generation=rec.generation,
                rotated_by=rec.rotated_by,
            )
