"""Audit workload: the periodic full-state sweep.

Counterpart of the reference's audit manager (pkg/audit/manager.go).
Where the reference's default path interprets one Rego query per cluster
object per sweep (manager.go:299-327 — the throughput hot loop), this
manager drives the whole sweep through one batched `Client.audit()` call
(the TPU driver's fused kernel dispatch), then applies the same
aggregation contract: per-constraint violation cap, message truncation,
and status publication with timestamps.
"""

from .manager import (  # noqa: F401
    AuditManager,
    AuditReport,
    ConstraintStatus,
    InMemorySink,
    StatusSink,
    Violation,
    truncate_message,
)
