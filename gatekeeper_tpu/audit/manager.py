"""Audit sweep manager.

Mirrors the behavioral contract of pkg/audit/manager.go:
  * sweep cadence `audit_interval` (default 60s, manager.go:42,344-358);
  * per-constraint violation cap `constraint_violations_limit`
    (default 20, manager.go:43,49,499-506);
  * violation messages truncated to `msg_size` bytes with a "..."
    suffix (manager.go:503,560-568);
  * per-constraint status records carrying audit timestamp, total
    violation count, and the capped violation details
    (manager.go:493-558), plus per-enforcement-action totals
    (manager.go:400-446).

The reference writes statuses to each Constraint CR's
`status.violations` via the K8s API with retry/backoff
(manager.go:581-639); here publication goes through a pluggable
`StatusSink` (in-memory by default; the control-plane layer provides a
cluster-backed one).

The data path difference is the point: instead of one interpreted query
per object (manager.go:318), one `Client.audit()` call sweeps the whole
cached state through the TPU driver's fused kernel.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..faults import fire

DEFAULT_AUDIT_INTERVAL = 60.0
DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT = 20
DEFAULT_MSG_SIZE = 256


def truncate_message(msg: str, size: int = DEFAULT_MSG_SIZE) -> str:
    """truncateString (manager.go:560-568): overlong messages keep the
    first size-3 chars plus '...'."""
    if len(msg) <= size:
        return msg
    if size > 3:
        size -= 3
    return msg[:size] + "..."


@dataclass
class Violation:
    """One entry of a constraint's status.violations list
    (apis/status/v1beta1 shape, populated by manager.go:509-520)."""

    message: str
    enforcement_action: str
    kind: str
    name: str
    namespace: str


@dataclass
class ConstraintStatus:
    """Aggregated per-constraint audit status."""

    constraint_kind: str
    constraint_name: str
    audit_timestamp: str
    total_violations: int
    violations: List[Violation] = field(default_factory=list)


@dataclass
class AuditReport:
    """One sweep's outcome."""

    timestamp: str
    duration_seconds: float
    total_violations: int
    by_enforcement_action: Dict[str, int]
    statuses: Dict[str, ConstraintStatus]  # key: "<Kind>/<name>"


class StatusSink:
    """Publication boundary for constraint statuses (the reference's
    equivalent is the status.violations API write loop)."""

    def publish(self, report: AuditReport) -> None:
        raise NotImplementedError


class InMemorySink(StatusSink):
    def __init__(self):
        self.reports: List[AuditReport] = []

    def publish(self, report: AuditReport) -> None:
        self.reports.append(report)

    @property
    def latest(self) -> Optional[AuditReport]:
        return self.reports[-1] if self.reports else None


class AuditManager:
    """Periodic audit sweeps over a constraint-framework Client."""

    def __init__(
        self,
        client,
        target: str,
        sink: Optional[StatusSink] = None,
        audit_interval: float = DEFAULT_AUDIT_INTERVAL,
        constraint_violations_limit: int = DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT,
        msg_size: int = DEFAULT_MSG_SIZE,
        now: Callable[[], float] = time.time,
        metrics=None,
        event_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        emit_audit_events: bool = False,
        # --audit-from-cache (manager.go:194-206): True sweeps the
        # synced OPA cache in one fused call; False mirrors the
        # reference DEFAULT — list every cluster GVK directly
        # (auditResources, manager.go:232-342) in --audit-chunk-size
        # batches through the batched review path, covering GVKs the
        # Config never syncs
        audit_from_cache: bool = True,
        cluster=None,
        audit_chunk_size: int = 512,
        excluder=None,
        logger=None,
        tracer=None,
        # boot barrier: the loop's FIRST sweep waits for this (the
        # runner passes wait_ready) so warmup runs on the fully
        # ingested state, not an empty cache — the warm sweep is what
        # closes the first-sweep compile cliff (VERDICT r3 #7)
        wait_for: Optional[Callable[[float], bool]] = None,
        # obs.DecisionLog: each audited violation leaves one decision
        # record (plane="audit") joined to the sweep's trace id, so the
        # decision stream covers BOTH admission-time and at-rest
        # verdicts (docs/observability.md §Decision log). The log's
        # rate gate bounds a million-violation sweep.
        decision_log=None,
    ):
        from ..logs import null_logger

        self.decision_log = decision_log
        self.log = logger if logger is not None else null_logger()
        # obs.Tracer: each sweep is one trace (audit_sweep root with
        # per-phase children — dispatch/list, aggregate, status_write)
        self.tracer = tracer
        self.wait_for = wait_for
        # set after the first completed sweep: the audit path is warm
        # (kernels compiled, corpus encoded+staged, render caches primed)
        self.warmed = threading.Event()
        self.client = client
        self.target = target
        self.audit_from_cache = audit_from_cache
        self.cluster = cluster
        # 0 keeps the upstream convention "no chunking" (manager.go:50);
        # negatives clamp to it. Positive values bound the list page
        # size on the wire.
        self.audit_chunk_size = max(0, int(audit_chunk_size))
        self.excluder = excluder
        self.sink = sink if sink is not None else InMemorySink()
        self.audit_interval = audit_interval
        self.violations_limit = constraint_violations_limit
        self.msg_size = msg_size
        self._now = now
        self.metrics = metrics
        # violation event emission (--emit-audit-events, manager.go:684)
        self.event_sink = event_sink
        self.emit_audit_events = emit_audit_events
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_run_seconds: Optional[float] = None
        self.audit_duration_seconds: Optional[float] = None
        self._reported_eas: set = set()
        self.last_error: Optional[BaseException] = None
        self.error_count = 0

    # -- one sweep -----------------------------------------------------------

    def audit(self) -> AuditReport:
        """One full sweep, then the reference's aggregation contract
        (cap, truncate, publish). From-cache mode sweeps the synced
        state in one fused Client.audit; direct mode lists the cluster
        GVK-by-GVK in chunks through the batched review path. Each
        sweep is one trace: audit_sweep -> dispatch (or per-kind
        list_and_review spans in direct mode) / aggregate /
        status_write, mirrored into `audit_phase_seconds`."""
        from ..obs import start_span

        t0 = self._now()
        timestamp = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(int(t0))
        )
        # every record of this sweep shares the audit id
        # (manager.go:148: am.log = log.WithValues(logging.AuditID, ts))
        log = self.log.with_values(process="audit", audit_id=timestamp)
        with start_span(
            self.tracer, "audit_sweep", audit_id=timestamp,
            from_cache=bool(self.audit_from_cache or self.cluster is None),
        ) as root:
            return self._audit_once(t0, timestamp, log, root)

    def _audit_once(self, t0, timestamp, log, root) -> AuditReport:
        from ..obs import start_span

        # wall stamps only label the report/spans; all phase DURATION
        # math below runs on perf_counter marks (time.time steps under
        # NTP, and a sweep is long enough to straddle a step)
        wall_disp0 = time.time()
        perf_disp0 = time.perf_counter()
        with start_span(self.tracer, "dispatch", parent=root) as dsp:
            if self.audit_from_cache or self.cluster is None:
                log.info("Auditing from cache")
                resp = self.client.audit().by_target.get(self.target)
                results = resp.results if resp is not None else []
            else:
                log.info("Auditing via discovery client")
                results = self._audit_resources()
            stats = getattr(
                getattr(self.client, "_driver", None), "stats", None
            )
            if isinstance(stats, dict):
                dsp.set_attr(
                    **{
                        k: stats[k]
                        for k in (
                            "compiled_pairs", "interp_pairs",
                            "hot_redispatches", "n_reviews",
                        )
                        if k in stats
                    }
                )
        perf_agg0 = time.perf_counter()
        wall_agg0 = wall_disp0 + (perf_agg0 - perf_disp0)
        statuses: Dict[str, ConstraintStatus] = {}
        totals_by_ea: Dict[str, int] = {}
        for r in results:
            ckind = (r.constraint or {}).get("kind", "?")
            cname = ((r.constraint or {}).get("metadata") or {}).get(
                "name", "?"
            )
            key = f"{ckind}/{cname}"
            st = statuses.get(key)
            if st is None:
                st = ConstraintStatus(
                    constraint_kind=ckind,
                    constraint_name=cname,
                    audit_timestamp=timestamp,
                    total_violations=0,
                )
                statuses[key] = st
            st.total_violations += 1
            ea = r.enforcement_action or "deny"
            totals_by_ea[ea] = totals_by_ea.get(ea, 0) + 1
            # cap (manager.go:499-506): count everything, detail the
            # first `violations_limit`
            if len(st.violations) < self.violations_limit:
                res = r.resource if isinstance(r.resource, dict) else {}
                meta = res.get("metadata") or {}
                st.violations.append(
                    Violation(
                        message=truncate_message(r.msg or "", self.msg_size),
                        enforcement_action=ea,
                        kind=res.get("kind", ""),
                        name=meta.get("name", ""),
                        namespace=meta.get("namespace", ""),
                    )
                )
            res_l = r.resource if isinstance(r.resource, dict) else {}
            meta_l = res_l.get("metadata") or {}
            if self.decision_log is not None:
                # per-violation decision record, joined to the sweep's
                # trace id; rate-gated + ring-bounded by the log itself
                self.decision_log.record_decision(
                    "audit",
                    "deny" if ea == "deny" else "dryrun",
                    code=200,
                    trace_id=getattr(root, "trace_id", None),
                    tenant={"namespace": meta_l.get("namespace", "")},
                    violations=[{
                        "constraint_kind": ckind,
                        "constraint_name": cname,
                        "action": ea,
                        "msg": truncate_message(
                            r.msg or "", self.msg_size
                        ),
                    }],
                    route="audit",
                    resource={
                        "kind": res_l.get("kind", ""),
                        "name": meta_l.get("name", ""),
                    },
                    audit_id=timestamp,
                )
            # logViolation (manager.go:668-682)
            log.info(
                truncate_message(r.msg or "", self.msg_size),
                event_type="violation_audited",
                constraint_kind=ckind,
                constraint_name=cname,
                constraint_action=ea,
                resource_kind=res_l.get("kind", ""),
                resource_namespace=meta_l.get("namespace", ""),
                resource_name=meta_l.get("name", ""),
            )
            if self.emit_audit_events and self.event_sink is not None:
                res = r.resource if isinstance(r.resource, dict) else {}
                meta = res.get("metadata") or {}
                self.event_sink(
                    {
                        "type": "Warning",
                        "reason": "AuditViolation",
                        "process": "audit",
                        "constraint_kind": ckind,
                        "constraint_name": cname,
                        "enforcement_action": ea,
                        "resource_kind": res.get("kind", ""),
                        "resource_namespace": meta.get("namespace", ""),
                        "resource_name": meta.get("name", ""),
                        "message": truncate_message(
                            r.msg or "", self.msg_size
                        ),
                    }
                )

        duration = self._now() - t0
        report = AuditReport(
            timestamp=timestamp,
            duration_seconds=duration,
            total_violations=len(results),
            by_enforcement_action=totals_by_ea,
            statuses=statuses,
        )
        log.info("audit results", violations=len(results))
        for st in statuses.values():
            # updateConstraintStatus log shape (manager.go:652-666)
            log.debug(
                "updating constraint status",
                constraint_kind=st.constraint_kind,
                constraint_name=st.constraint_name,
                constraint_status="enforced",
                constraint_violations=str(st.total_violations),
            )
        perf_pub0 = time.perf_counter()
        wall_pub0 = wall_disp0 + (perf_pub0 - perf_disp0)
        try:
            # named fault point (docs/robustness.md): a K8s status-write
            # error — the reference's retry-with-backoff surface
            fire("audit.status_write")
            self.sink.publish(report)
        except Exception as e:
            # a failed status write must not void the sweep: the report
            # is still returned (and the next sweep re-publishes the
            # full state — statuses are absolute, not deltas)
            if self.metrics is not None:
                self.metrics.record("audit_status_write_failures_total", 1)
            log.error(
                "constraint status publish failed; next sweep will "
                "re-publish",
                err=e,
                trace_id=getattr(root, "trace_id", None),
            )
            if root is not None:
                root.set_attr(status_write_error=str(e))
        perf_pub1 = time.perf_counter()
        wall_pub1 = wall_disp0 + (perf_pub1 - perf_disp0)
        if self.tracer is not None:
            # aggregate/status_write stamped from timing marks instead
            # of open spans: an exception mid-aggregation must not leave
            # a dangling open span pinning the sweep trace
            self.tracer.record_span(
                "aggregate", wall_agg0, wall_pub0, parent=root,
                violations=len(results),
            )
            self.tracer.record_span(
                "status_write", wall_pub0, wall_pub1, parent=root,
                statuses=len(statuses),
            )
        self.last_run_seconds = t0
        self.audit_duration_seconds = duration
        if self.metrics is not None:
            for phase, dt in (
                ("dispatch", perf_agg0 - perf_disp0),
                ("aggregate", perf_pub0 - perf_agg0),
                ("status_write", perf_pub1 - perf_pub0),
            ):
                self.metrics.observe(
                    "audit_phase_seconds", dt, phase=phase
                )
            # the audit stats reporter's metric surface
            # (pkg/audit/stats_reporter.go; docs/Metrics.md:83-104);
            # enforcement actions seen in PRIOR sweeps re-report 0 when
            # their violations clear, so series never go stale
            self.metrics.observe("audit_duration_seconds", duration)
            self.metrics.gauge("audit_last_run_time", t0)
            for ea in set(totals_by_ea) | self._reported_eas:
                self.metrics.gauge(
                    "violations",
                    totals_by_ea.get(ea, 0),
                    enforcement_action=ea,
                )
            self._reported_eas |= set(totals_by_ea)
        return report

    def _audit_resources(self) -> List[Any]:
        """The reference's default path (auditResources,
        manager.go:232-342): list EVERY listable cluster GVK — synced
        or not — skipping gatekeeper's own kinds, and review objects in
        audit-chunk-size batches (each batch is one fused device
        dispatch via review_many; the reference issues one interpreted
        query per object here)."""
        skip_groups = {
            "constraints.gatekeeper.sh",
            "templates.gatekeeper.sh",
            "config.gatekeeper.sh",
            "status.gatekeeper.sh",
        }
        from ..control.events import GVK

        ns_gvk = GVK("", "v1", "Namespace")
        ns_cache: Dict[str, Any] = {}  # per-sweep (nsCache, manager.go:299)
        results: List[Any] = []
        list_pages = getattr(self.cluster, "list_pages", None)
        for gvk in sorted(self.cluster.known_gvks()):
            if gvk.group in skip_groups:
                continue
            if self.audit_chunk_size <= 0:
                # chunking disabled (the upstream --audit-chunk-size=0
                # convention): one in-memory list, one page
                pages = iter([self.cluster.list(gvk)])
            elif list_pages is not None:
                # stream apiserver pages at --audit-chunk-size: bounded
                # memory per kind (the reference's paged List w/
                # Continue, manager.go:277-298), one fused review_many
                # dispatch per page
                pages = list_pages(gvk, self.audit_chunk_size)
            else:
                objs = self.cluster.list(gvk)
                pages = (
                    objs[start : start + self.audit_chunk_size]
                    for start in range(0, len(objs), self.audit_chunk_size)
                )
            # per-kind containment: one kind failing (transient 5xx, an
            # unpageable aggregated API) must not abort the whole sweep
            # — the reference logs and moves to the next kind
            # (manager.go:277-298's error branches)
            wall_kind, perf_kind = time.time(), time.perf_counter()
            try:
                kind_results = self._review_pages(pages, ns_cache, ns_gvk)
            except Exception as e:
                self.log.error(
                    "audit list/review failed for kind",
                    err=e,
                    gvk=str(gvk),
                )
                if self.tracer is not None:
                    self.tracer.record_window(
                        "list_and_review", wall_kind, perf_kind,
                        parent=self.tracer.current(), status="error",
                        gvk=str(gvk), error=str(e),
                    )
                continue
            if self.tracer is not None:
                # one span per kind under the sweep's dispatch span
                # (direct mode's list/chunk/review phase)
                self.tracer.record_window(
                    "list_and_review", wall_kind, perf_kind,
                    parent=self.tracer.current(),
                    gvk=str(gvk), results=len(kind_results),
                )
            results.extend(kind_results)
        return results

    def _review_pages(self, pages, ns_cache, ns_gvk) -> List[Any]:
        """Review one kind's page stream; a None RESTART marker (410
        continue-token expiry -> full relist) discards the partial
        results so objects are never double-counted."""
        from ..control.process import PROCESS_AUDIT

        from ..constraint.handler import handler_for

        handler = handler_for(self.client, self.target)
        results: List[Any] = []
        for chunk in pages:
            if chunk is None:  # RESTART: pagination began again
                results = []
                continue
            reviews = []
            for obj in chunk:
                ns = (obj.get("metadata") or {}).get("namespace") or ""
                if (
                    ns
                    and self.excluder is not None
                    and self.excluder.is_namespace_excluded(
                        PROCESS_AUDIT, ns
                    )
                ):
                    continue
                # attach the Namespace object (the reference's
                # nsCache.Get, manager.go:299-317) — without it the
                # review carries no namespace and every constraint-
                # level namespace match degrades to cluster-scoped.
                # A namespaced object whose Namespace is missing is
                # SKIPPED like the reference's lookup-failure path
                # (manager.go:307-311 logs and continues).
                if ns:
                    if ns not in ns_cache:
                        ns_cache[ns] = self.cluster.get(ns_gvk, "", ns)
                    ns_obj = ns_cache[ns]
                    if ns_obj is None:
                        continue
                    reviews.append(handler.wrap_audit_object(obj, ns_obj))
                else:
                    reviews.append(handler.wrap_audit_object(obj, None))
            if not reviews:
                continue
            for responses in self.client.review_many(reviews):
                resp = responses.by_target.get(self.target)
                if resp is not None:
                    results.extend(resp.results)
        return results

    # -- sweep loop (auditManagerLoop, manager.go:344-358) -------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        if self.wait_for is not None:
            try:
                fire("audit.barrier")  # chaos: simulate a barrier fault
                self.wait_for(300.0)
            except Exception as e:
                # barrier failure: sweep anyway (fail-open posture) —
                # but NEVER silently. The first sweep running against a
                # partially ingested cache under-reports violations; an
                # operator must be able to see that happened (counter)
                # and find the why (trace + correlated log record).
                trace_id = None
                if self.tracer is not None:
                    with self.tracer.start_span(
                        "audit_barrier_failure", error=str(e)
                    ) as sp:
                        trace_id = sp.trace_id
                if self.metrics is not None:
                    self.metrics.record("audit_barrier_failures_total", 1)
                self.log.error(
                    "audit boot barrier failed; sweeping anyway "
                    "(first sweep may run on partially ingested state)",
                    process="audit",
                    trace_id=trace_id,
                    err=e,
                )
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.audit()
                self.last_error = None
                self.warmed.set()
            except Exception as e:  # sweep failures don't kill the loop
                self.last_error = e
                self.error_count += 1
            # fixed cadence like the reference's ticker (manager.go:
            # 344-358): the next sweep starts `audit_interval` after the
            # previous one STARTED, not after it finished
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.0, self.audit_interval - elapsed))
