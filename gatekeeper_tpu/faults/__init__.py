"""Robustness toolkit for the admission plane: deterministic fault
injection (`injection.py`), the device circuit breaker (`breaker.py`),
and the overload/degradation error taxonomy (`errors.py`). The failure
envelope, degradation ladder, and fault-point catalog are documented in
docs/robustness.md.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .errors import (
    AdmissionUnavailable,
    DeadlineExceeded,
    EvaluationTimeout,
    EvaluationUnavailable,
    ShedError,
)
from .injection import (
    FAULTS,
    FaultError,
    FaultRegistry,
    FaultSpec,
    configure_from_env,
    device_point,
    fire,
    skew,
)

__all__ = [
    "AdmissionUnavailable",
    "CircuitBreaker",
    "CLOSED",
    "DeadlineExceeded",
    "EvaluationTimeout",
    "EvaluationUnavailable",
    "FAULTS",
    "FaultError",
    "FaultRegistry",
    "FaultSpec",
    "HALF_OPEN",
    "OPEN",
    "ShedError",
    "configure_from_env",
    "device_point",
    "fire",
    "skew",
]
