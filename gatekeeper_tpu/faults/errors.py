"""Overload/degradation error taxonomy for the admission plane.

These errors mean "the evaluation DID NOT HAPPEN" — the request was
shed before dispatch, expired before dispatch, or every evaluation
rung was down. They are distinct from evaluation errors (a poisoned
request failing on the interpreter stays a 500): the handler answers
them with the endpoint's configured fail-open/fail-closed envelope
instead, mirroring what the apiserver's failurePolicy would do if the
webhook had simply timed out — but explicitly, countably, and within
the caller's deadline.
"""

from __future__ import annotations


class AdmissionUnavailable(RuntimeError):
    """Base: the request was never evaluated; respond per fail policy."""

    reason = "unavailable"


class ShedError(AdmissionUnavailable):
    """Dropped by the admission scheduler under overload.

    `reason` distinguishes the shed classes (decision records carry it):
    `queue_full` (bounded queue at capacity), `predicted_miss` (the
    scheduler proved the deadline unmakeable — `predicted_slack_ms` is
    the negative slack), `tenant_capped` (per-tenant fair-share quota
    exhausted while the plane is overloaded). `tenant_capped` also
    rides as a boolean alongside the other reasons: whether the tenant
    was over its share when the shed happened."""

    reason = "queue_full"

    def __init__(
        self,
        message: str = "",
        reason: str = None,
        predicted_slack_ms: float = None,
        tenant_capped: bool = False,
    ):
        super().__init__(message)
        if reason is not None:
            self.reason = reason
        self.predicted_slack_ms = predicted_slack_ms
        self.tenant_capped = tenant_capped


class DeadlineExceeded(AdmissionUnavailable):
    """The caller's deadline expired before dispatch — evaluating now
    would burn device time on an answer nobody is waiting for."""

    reason = "deadline"


class EvaluationUnavailable(AdmissionUnavailable):
    """Every evaluation rung was down (device faulted AND the host
    oracle was unavailable) — the bottom of the degradation ladder."""

    reason = "degraded"


class EvaluationTimeout(AdmissionUnavailable):
    """The in-flight evaluation outlived the request timeout (a hung
    device dispatch); the caller gets the policy envelope while the
    worker finishes or dies in the background."""

    reason = "timeout"
