"""Deterministic fault-injection registry.

The admission plane concentrates all risk in one device dispatch: a
failure envelope that is only ever exercised in production is not a
failure envelope, it is a surprise. This registry gives every
interesting failure surface a NAMED fault point threaded into the
production code path itself (`fire("driver.device_dispatch")` sits
inside `TpuDriver._need_pairs`, not in a test double), so the chaos
suite and `bench_webhook.py --chaos` drive the REAL degradation ladder
— fused TPU → host oracle → fail-open verdict — end to end.

Semantics (arm / trigger / fire):
  * `arm(point, mode, ...)` registers a fault spec for a point;
  * every pass through the point is a HIT; the spec triggers only
    after `after` hits have been skipped (deterministic ordering, no
    randomness — chaos runs must be replayable);
  * a triggered spec FIRES at most `count` times (-1 = forever):
    mode "error" raises `FaultError`, mode "hang" sleeps `delay_s`
    then continues (a stall, not a crash), mode "clock_jump" never
    raises — callers that do deadline arithmetic consult `skew()` to
    learn the injected clock offset.

Activation: tier-1 stays clean because nothing is armed by default and
`fire()` is a single boolean check when the registry is empty.
Deployments opt in with
`GATEKEEPER_TPU_FAULTS="point=mode[:key=value...],..."`, e.g.

    GATEKEEPER_TPU_FAULTS="driver.device_dispatch=error:count=5,\
bridge.process=hang:delay=0.25"

The fault-point catalog lives in docs/robustness.md.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

MODES = ("error", "hang", "clock_jump")

# point=mode[:modifiers]; the point may itself contain '=' inside a
# device label ("driver.device_dispatch[device=1]=error"), so the split
# anchors on the first '=' that is followed by a KNOWN mode, not on the
# first '=' in the entry
_ENTRY_RE = re.compile(
    r"^(?P<point>.+?)=(?P<mode>" + "|".join(MODES) + r")(?::(?P<rest>.*))?$"
)


def device_point(point: str, device) -> str:
    """Device-labeled fault point name (docs/robustness.md §Fault
    domains): `device_point("driver.device_dispatch", 1)` ->
    `"driver.device_dispatch[device=1]"`. The label is part of the
    point NAME, so arm/hit/fire accounting — and the env-string grammar
    — stays exact per device with zero new registry machinery."""
    return f"{point}[device={device}]"


class FaultError(RuntimeError):
    """The injected failure. Deliberately a plain RuntimeError subclass:
    production code must survive it via the SAME handling it gives real
    faults, never by special-casing injection."""

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"injected fault at {point}")
        self.point = point


@dataclass
class FaultSpec:
    """One armed fault point."""

    point: str
    mode: str = "error"
    count: int = -1  # fires at most `count` times; -1 = forever
    after: int = 0  # skip the first `after` hits before triggering
    delay_s: float = 0.05  # hang sleep / clock_jump offset (seconds)
    message: str = ""
    hits: int = field(default=0)  # passes through the point
    fired: int = field(default=0)  # times the fault actually fired


class FaultRegistry:
    """Thread-safe arm/trigger/fire registry (module-global `FAULTS` is
    the instance every production fault point consults)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: Dict[str, FaultSpec] = {}
        # fast-path flag: fire() must cost one attribute read when
        # nothing is armed (the tier-1 / steady-state case)
        self._active = False

    # -- arming --------------------------------------------------------------

    def arm(
        self,
        point: str,
        mode: str = "error",
        count: int = -1,
        after: int = 0,
        delay_s: float = 0.05,
        message: str = "",
    ) -> FaultSpec:
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} (want {MODES})")
        spec = FaultSpec(
            point=point, mode=mode, count=count, after=after,
            delay_s=delay_s, message=message,
        )
        with self._lock:
            self._specs[point] = spec
            self._active = True
        return spec

    def disarm(self, point: Optional[str] = None) -> None:
        """Disarm one point (or every point when None). Hit/fire counts
        die with the spec — read them via `spec()` before disarming."""
        with self._lock:
            if point is None:
                self._specs.clear()
            else:
                self._specs.pop(point, None)
            self._active = bool(self._specs)

    def reset(self) -> None:
        self.disarm(None)

    # -- introspection -------------------------------------------------------

    def spec(self, point: str) -> Optional[FaultSpec]:
        with self._lock:
            return self._specs.get(point)

    def hits(self, point: str) -> int:
        with self._lock:
            s = self._specs.get(point)
            return s.hits if s is not None else 0

    def fired(self, point: str) -> int:
        with self._lock:
            s = self._specs.get(point)
            return s.fired if s is not None else 0

    def active(self) -> bool:
        return self._active

    def snapshot(self) -> Dict[str, Dict]:
        """Armed-point inventory with hit/fire counts — the soak
        reporter logs this at each disarm so the evidence artifact
        carries WHICH faults fired and how often, not just that an SLO
        dip happened around the right timestamp."""
        with self._lock:
            return {
                point: {
                    "mode": s.mode,
                    "count": s.count,
                    "after": s.after,
                    "delay_s": s.delay_s,
                    "hits": s.hits,
                    "fired": s.fired,
                }
                for point, s in self._specs.items()
            }

    # -- the fault point ----------------------------------------------------

    def fire(self, point: str) -> None:
        """Called at a production fault point. No-op unless the point is
        armed and its trigger condition holds; then raises (error),
        stalls (hang), or no-ops (clock_jump — see `skew`)."""
        if not self._active:
            return
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return
            spec.hits += 1
            if spec.hits <= spec.after:
                return
            if spec.count >= 0 and spec.fired >= spec.count:
                return
            spec.fired += 1
            mode, delay_s, message = spec.mode, spec.delay_s, spec.message
        if mode == "hang":
            # a stall, not a crash: the caller proceeds afterwards (the
            # deadline/timeout machinery is what must save the request)
            time.sleep(delay_s)
            return
        if mode == "error":
            raise FaultError(point, message)
        # clock_jump: consulted via skew(), never raises at the point

    def skew(self, point: str) -> float:
        """Injected clock offset (seconds) for an armed clock_jump at
        `point`; 0.0 otherwise. Honors the same after/count trigger
        semantics as fire(), so a chaos run can place the jump at a
        deterministic consultation (e.g. AFTER a deadline was computed
        but before it is checked — a real NTP step lands between two
        reads of the clock, not at process start)."""
        if not self._active:
            return 0.0
        with self._lock:
            spec = self._specs.get(point)
            if spec is None or spec.mode != "clock_jump":
                return 0.0
            spec.hits += 1
            if spec.hits <= spec.after:
                return 0.0
            if spec.count >= 0 and spec.fired >= spec.count:
                return 0.0
            spec.fired += 1
            return spec.delay_s


# the registry every production fault point consults
FAULTS = FaultRegistry()


def fire(point: str) -> None:
    FAULTS.fire(point)


def skew(point: str) -> float:
    return FAULTS.skew(point)


def configure_from_env(registry: Optional[FaultRegistry] = None,
                       env: Optional[str] = None) -> int:
    """Parse GATEKEEPER_TPU_FAULTS into armed specs. Grammar (commas
    separate entries, colons separate modifiers):

        point=mode[:count=N][:after=N][:delay=S][:message=...]

    Returns the number of points armed. Unparseable entries are
    skipped — a typo in a chaos knob must not take the pod down."""
    registry = registry if registry is not None else FAULTS
    raw = env if env is not None else os.environ.get(
        "GATEKEEPER_TPU_FAULTS", ""
    )
    armed = 0
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        m = _ENTRY_RE.match(entry)
        if m is None:
            continue
        point, mode = m.group("point"), m.group("mode")
        rest = m.group("rest") or ""
        kwargs = {}
        ok = True
        for part in rest.split(":") if rest else ():
            key, _, val = part.partition("=")
            try:
                if key == "count":
                    kwargs["count"] = int(val)
                elif key == "after":
                    kwargs["after"] = int(val)
                elif key == "delay":
                    kwargs["delay_s"] = float(val)
                elif key == "message":
                    kwargs["message"] = val
                else:
                    ok = False
            except ValueError:
                ok = False
        if not ok:
            continue
        registry.arm(point.strip(), mode=mode, **kwargs)
        armed += 1
    return armed


# env-armed faults activate at import so every plane (driver, webhook,
# bridge, audit) sees the same registry without explicit wiring
configure_from_env()
