"""Circuit breaker around the fused device path.

Before this existed, a persistently failing device made EVERY batch pay
a doomed fused attempt plus N serial fallbacks — the failure tax scaled
with traffic exactly when the system was least healthy. The breaker
converts that into a state machine with an explicit, observable
envelope:

    CLOSED ──k consecutive failures──▶ OPEN
      ▲                                 │ recovery_seconds elapse
      │ probe succeeds                  ▼
      └───────────────────────────  HALF_OPEN ──probe fails──▶ OPEN

  * CLOSED: fused dispatches flow; consecutive failures are counted
    (any success resets the count).
  * OPEN: the fused path is short-circuited — batches go straight to
    the host-interpreter degraded mode, paying zero doomed device
    attempts. After `recovery_seconds` the breaker half-opens.
  * HALF_OPEN: exactly ONE batch is admitted as a probe; success closes
    the breaker, failure re-opens it (and restarts the recovery clock).

Every transition gets a Prometheus series (`device_breaker_state`,
`device_breaker_transitions_total`, `device_breaker_probes_total`) and
a tracer span, so a dashboard — not a log dive — answers "why is
admission on the interpreter right now".
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# gauge encoding for device_breaker_state
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure circuit breaker with half-open probes.

    Thread-safe; `allow()` / `record_success()` / `record_failure()`
    are the whole contract. `clock` is injectable so tests advance the
    recovery window deterministically."""

    def __init__(
        self,
        failure_threshold: int = 3,
        recovery_seconds: float = 30.0,
        plane: str = "validation",
        metrics=None,
        tracer=None,
        clock: Callable[[], float] = time.monotonic,
        # device fault domains (docs/robustness.md §Fault domains): a
        # per-device breaker carries its device id as a metric tag and
        # in its name, so multi-breaker accounting (transition ledgers,
        # fleet gossip keys, snapshots) stays exact per breaker instead
        # of assuming one breaker per plane
        device=None,
        name: Optional[str] = None,
        # obs.FlightRecorder: a transition to OPEN trips a postmortem
        # capture (trigger() is queue-and-wake, safe under this lock)
        recorder=None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_seconds = recovery_seconds
        self.plane = plane
        self.device = None if device is None else str(device)
        self.name = name or (
            f"device:{plane}:{self.device}"
            if self.device is not None
            else f"device:{plane}"
        )
        # the tag set every metric emission carries; the device tag is
        # only added when set, so single-breaker planes keep their
        # pre-partitioning series shape
        self._tags = {"plane": plane}
        if self.device is not None:
            self._tags["device"] = self.device
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_in_flight = False
        self.transitions = 0  # lifetime transition count (tests/readyz)
        self.adoptions = 0  # peer states adopted via adopt() (readyz)
        # transition listeners (the fleet plane's gossip hook). Called
        # INSIDE the breaker lock — listeners must be non-blocking and
        # must never call back into the breaker (set a flag / wake an
        # event; the fleet publisher drains asynchronously).
        self._listeners = []
        self._export_state()

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def snapshot(self) -> dict:
        """Readyz/debug view of the breaker, keyed by its name so
        multi-breaker planes (one per device) snapshot unambiguously."""
        with self._lock:
            self._maybe_half_open_locked()
            return {
                "name": self.name,
                "device": self.device,
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "transitions": self.transitions,
                "adoptions": self.adoptions,
                "probe_in_flight": self._probe_in_flight,
            }

    def _maybe_half_open_locked(self) -> None:
        if (
            self._state == OPEN
            and self._opened_at is not None
            and self._clock() - self._opened_at >= self.recovery_seconds
        ):
            self._transition_locked(HALF_OPEN)

    def _transition_locked(self, to_state: str) -> None:
        from_state = self._state
        if from_state == to_state:
            return
        self._state = to_state
        self.transitions += 1
        if to_state == OPEN:
            self._opened_at = self._clock()
            self._probe_in_flight = False
        elif to_state == HALF_OPEN:
            self._probe_in_flight = False
        else:  # CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._probe_in_flight = False
        self._export_state()
        for listener in self._listeners:
            try:
                listener(from_state, to_state)
            except Exception:
                pass  # gossip is best-effort; the breaker must not die
        if self.metrics is not None:
            self.metrics.record(
                "device_breaker_transitions_total", 1, **self._tags,
                from_state=from_state, to_state=to_state,
            )
        if self.tracer is not None:
            # a standalone one-span trace: transitions are rare and must
            # be findable in /debug/traces without a request to ride on
            with self.tracer.start_span(
                "breaker_transition", breaker=self.name, **self._tags,
                from_state=from_state, to_state=to_state,
            ):
                pass
        if self.recorder is not None and to_state == OPEN:
            # trip-triggered postmortem (docs/observability.md §Flight
            # recorder): trigger() only enqueues — safe under this lock
            try:
                self.recorder.trigger(
                    "breaker_open", breaker=self.name, **self._tags,
                    from_state=from_state, to_state=to_state,
                    consecutive_failures=self._consecutive_failures,
                )
            except Exception:
                pass

    def _export_state(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge(
                "device_breaker_state", _STATE_VALUE[self._state],
                **self._tags,
            )

    # -- fleet gossip ---------------------------------------------------------

    def subscribe(self, listener) -> None:
        """Register a `listener(from_state, to_state)` transition hook
        (the fleet plane's publish trigger). Called inside the breaker
        lock: must be non-blocking and must not re-enter the breaker."""
        with self._lock:
            self._listeners.append(listener)

    def adopt(self, peer_state: str) -> bool:
        """Adopt a peer replica's breaker verdict (docs/fleet.md):

          * peer OPEN while we are CLOSED → pre-open to HALF_OPEN: the
            next batch is a single probe instead of
            `failure_threshold` full batches rediscovering the outage;
          * peer CLOSED while we are OPEN → HALF_OPEN early: the peer's
            success is evidence recovery happened, probe now rather
            than waiting out the local recovery window.

        A peer's HALF_OPEN is deliberately NOT adopted: it means the
        peer is *probing*, not that an outage is confirmed — and
        adopting it ping-pongs two recovered replicas between CLOSED
        and HALF_OPEN forever (A closes, B adopts B's-recovery-induced
        HALF_OPEN back, ...), which on a quiet plane never settles
        (surfaced by the soak lane's breaker transition log).

        Never adopts straight to OPEN — a peer's outage is a hint, not
        proof, for THIS replica's device/endpoint; the probe decides.
        Returns True when a transition happened."""
        with self._lock:
            self._maybe_half_open_locked()
            if peer_state == OPEN and self._state == CLOSED:
                self._transition_locked(HALF_OPEN)
            elif peer_state == CLOSED and self._state == OPEN:
                self._transition_locked(HALF_OPEN)
            else:
                return False
            self.adoptions += 1
            return True

    # -- the contract --------------------------------------------------------

    def allow(self) -> bool:
        """May this batch take the fused device path? OPEN → no;
        HALF_OPEN → yes for exactly one probe batch at a time."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                if self.metrics is not None:
                    self.metrics.record(
                        "device_breaker_probes_total", 1,
                        **self._tags, result="success",
                    )
                self._transition_locked(CLOSED)
            else:
                self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                if self.metrics is not None:
                    self.metrics.record(
                        "device_breaker_probes_total", 1,
                        **self._tags, result="failure",
                    )
                self._transition_locked(OPEN)
                return
            self._consecutive_failures += 1
            if (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._transition_locked(OPEN)
