"""Wire-speed ingest plane (docs/ingest.md): framed streaming
transport, zero-copy AdmissionReview decode into encoder token rows,
connection-aware hand-off to the micro-batchers."""

from .decode import DecodeSurprise, LazyObject, decode_review, scan_review
from .transport import (
    FLAG_DEADLINE,
    FRAME_ERROR,
    FRAME_PING,
    FRAME_PONG,
    FRAME_RESPONSE,
    FRAME_VERSION,
    Frame,
    FrameReader,
    PLANE_AGENT,
    PLANE_LABEL,
    PLANE_MUTATE,
    PLANE_VALIDATE,
    ProtocolError,
    StreamClient,
    StreamListener,
    encode_frame,
)
from .server import IngestServer

__all__ = [
    "DecodeSurprise",
    "FLAG_DEADLINE",
    "FRAME_ERROR",
    "FRAME_PING",
    "FRAME_PONG",
    "FRAME_RESPONSE",
    "FRAME_VERSION",
    "Frame",
    "FrameReader",
    "IngestServer",
    "LazyObject",
    "PLANE_AGENT",
    "PLANE_LABEL",
    "PLANE_MUTATE",
    "PLANE_VALIDATE",
    "ProtocolError",
    "StreamClient",
    "StreamListener",
    "decode_review",
    "encode_frame",
    "scan_review",
]
