"""Framed streaming transport (docs/ingest.md §Wire format).

The legacy front door is stdlib `ThreadingHTTPServer`: one TCP
connection, one thread, one full HTTP parse per admission. At the
rates the accelerated evaluator sustains, connection setup and header
parsing dominate. This module is the replacement path: persistent
multiplexed connections carrying length-prefixed frames, so thousands
of in-flight admissions share a handful of sockets.

Wire format — every frame is:

    u32 big-endian  length of (header + payload)
    16-byte header  struct ">BBBBIQ":
        u8   version        (FRAME_VERSION = 1)
        u8   frame type     request plane tag or response/error/ping
        u8   flags          FLAG_DEADLINE: budget field is meaningful
        u8   reserved       (0 on the wire)
        u32  budget         request: deadline budget in ms from frame
                            arrival; response: HTTP-equivalent status
        u64  request id     client-chosen correlation id
    payload             request: AdmissionReview JSON bytes
                        response: envelope JSON bytes

Request planes mirror the legacy URL map: 'V' /v1/admit,
'M' /v1/mutate, 'A' /v1/agent/review, 'L' /v1/admitlabel.

Flow control: the per-connection reader thread blocks once
`max_inflight` frames from that connection are being served — TCP
backpressure does the rest. Bounds (`max_frame`, `max_inflight`) and
typed `ProtocolError`s shed the offending CONNECTION (best-effort
error frame, then close); a malformed peer can never take the
listener down.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time as _time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

__all__ = [
    "BadFrameType",
    "BadVersion",
    "DEFAULT_MAX_FRAME",
    "DEFAULT_MAX_INFLIGHT",
    "FLAG_DEADLINE",
    "FRAME_ERROR",
    "FRAME_HEADER",
    "FRAME_PING",
    "FRAME_PONG",
    "FRAME_RESPONSE",
    "FRAME_VERSION",
    "Frame",
    "FrameReader",
    "FrameTooLarge",
    "PLANE_AGENT",
    "PLANE_LABEL",
    "PLANE_MUTATE",
    "PLANE_VALIDATE",
    "ProtocolError",
    "REQUEST_PLANES",
    "ShortFrame",
    "StreamClient",
    "StreamListener",
    "encode_frame",
]

FRAME_VERSION = 1
FRAME_HEADER = struct.Struct(">BBBBIQ")
_LEN_PREFIX = struct.Struct(">I")

PLANE_VALIDATE = 0x56  # 'V' -> /v1/admit
PLANE_MUTATE = 0x4D    # 'M' -> /v1/mutate
PLANE_AGENT = 0x41     # 'A' -> /v1/agent/review
PLANE_LABEL = 0x4C     # 'L' -> /v1/admitlabel
FRAME_RESPONSE = 0x52  # 'R'
FRAME_ERROR = 0x45     # 'E'
FRAME_PING = 0x50      # 'P'
FRAME_PONG = 0x51      # 'Q'

REQUEST_PLANES: Dict[int, str] = {
    PLANE_VALIDATE: "validation",
    PLANE_MUTATE: "mutation",
    PLANE_AGENT: "agent",
    PLANE_LABEL: "label",
}
_KNOWN_TYPES = frozenset(REQUEST_PLANES) | {
    FRAME_RESPONSE, FRAME_ERROR, FRAME_PING, FRAME_PONG,
}

FLAG_DEADLINE = 0x01

DEFAULT_MAX_FRAME = 4 * 1024 * 1024  # payload bound, bytes
DEFAULT_MAX_INFLIGHT = 256           # frames being served, per conn


class ProtocolError(Exception):
    """Wire-level violation: sheds the connection, never the
    listener. `code` slugs label `ingest_protocol_errors_total`."""

    code = "protocol"


class FrameTooLarge(ProtocolError):
    code = "frame_too_large"


class ShortFrame(ProtocolError):
    code = "short_frame"


class BadVersion(ProtocolError):
    code = "bad_version"


class BadFrameType(ProtocolError):
    code = "bad_frame_type"


class TruncatedStream(ProtocolError):
    code = "truncated_stream"


class InflightExceeded(ProtocolError):
    code = "inflight_exceeded"


class Frame(NamedTuple):
    ftype: int
    flags: int
    budget: int       # request: deadline ms; response: status code
    request_id: int
    payload: memoryview


def encode_frame(
    ftype: int,
    request_id: int,
    payload: bytes = b"",
    budget: int = 0,
    flags: Optional[int] = None,
) -> bytes:
    """One wire frame (length prefix + header + payload)."""
    if flags is None:
        flags = FLAG_DEADLINE if budget else 0
    hdr = FRAME_HEADER.pack(
        FRAME_VERSION, ftype, flags, 0, budget, request_id
    )
    return _LEN_PREFIX.pack(FRAME_HEADER.size + len(payload)) + hdr + payload


class FrameReader:
    """Incremental frame parser — feed it whatever recv() returned,
    get back every complete frame. One per connection; a raised
    ProtocolError poisons the reader and the connection is shed."""

    def __init__(self, max_frame: int = DEFAULT_MAX_FRAME):
        self._buf = bytearray()
        self.max_frame = max_frame

    def pending_bytes(self) -> int:
        return len(self._buf)

    def feed(self, data: bytes) -> List[Frame]:
        self._buf += data
        frames: List[Frame] = []
        buf = self._buf
        while True:
            if len(buf) < 4:
                break
            length = int.from_bytes(buf[:4], "big")
            if length < FRAME_HEADER.size:
                raise ShortFrame(f"frame length {length}")
            if length > self.max_frame + FRAME_HEADER.size:
                raise FrameTooLarge(f"frame length {length}")
            if len(buf) < 4 + length:
                break
            mv = memoryview(buf)
            blob = bytes(mv[4:4 + length])
            mv.release()
            del buf[:4 + length]
            version, ftype, flags, _, budget, rid = FRAME_HEADER.unpack_from(
                blob, 0
            )
            if version != FRAME_VERSION:
                raise BadVersion(f"version {version}")
            if ftype not in _KNOWN_TYPES:
                raise BadFrameType(f"type 0x{ftype:02x}")
            frames.append(
                Frame(
                    ftype, flags, budget, rid,
                    memoryview(blob)[FRAME_HEADER.size:],
                )
            )
        return frames


class _Conn:
    __slots__ = ("sock", "addr", "wlock", "cv", "inflight", "open")

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.wlock = threading.Lock()
        self.cv = threading.Condition()
        self.inflight = 0
        self.open = True


class StreamListener:
    """Accept loop + one reader thread per connection + a shared
    worker pool running `frame_handler(frame) -> (status, payload)`
    for each request frame. Responses are written back on the frame's
    connection under a per-connection write lock (frames from one
    socket complete out of order; the request id correlates)."""

    def __init__(
        self,
        frame_handler: Callable[[Frame], Tuple[int, bytes]],
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame: int = DEFAULT_MAX_FRAME,
        max_inflight: int = DEFAULT_MAX_INFLIGHT,
        workers: int = 64,
        metrics=None,
        backlog: int = 512,
    ):
        self.frame_handler = frame_handler
        self.max_frame = max_frame
        self.max_inflight = max_inflight
        self.metrics = metrics
        self._lock = threading.Lock()
        self._conns: Dict[int, _Conn] = {}
        self._next_conn = 0
        self._stopping = False
        self._stats = {
            "connections_total": 0,
            "frames_total": 0,
            "protocol_errors_total": 0,
            "bytes_in": 0,
            "bytes_out": 0,
            "shed_connections": 0,
        }
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="ingest-worker"
        )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(backlog)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ingest-accept", daemon=True
        )
        self._accept_thread.start()

    def stop_accepting(self) -> None:
        with self._lock:
            self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass

    def drain(self, timeout: float = 2.0) -> bool:
        """Wait (bounded) until no frame is being served — the last
        step between the webhook's own inflight wait and the response
        WRITE, which happens on the pool after the handler returns."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                busy = any(
                    c.inflight > 0 for c in self._conns.values()
                )
            if not busy:
                return True
            _time.sleep(0.01)
        return False

    def close(self) -> None:
        """Full stop: no new connections, shed the live ones, drain
        the pool. Callers wanting graceful drain wait on their own
        inflight accounting first (webhook/server.py does)."""
        self.stop_accepting()
        self.drain()
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            self._close_conn(conn)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        self._pool.shutdown(wait=False)

    # -- stats / metrics -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
            out["connections_active"] = len(self._conns)
            out["inflight"] = sum(c.inflight for c in self._conns.values())
        return out

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    def _gauge_conns(self) -> None:
        if self.metrics is not None:
            with self._lock:
                n = len(self._conns)
            self.metrics.gauge("ingest_connections_active", n)

    # -- accept / read -------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return  # listening socket closed
            with self._lock:
                if self._stopping:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                cid = self._next_conn
                self._next_conn += 1
                conn = _Conn(sock, addr)
                self._conns[cid] = conn
                self._stats["connections_total"] += 1
            try:
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            if self.metrics is not None:
                self.metrics.record("ingest_connections_total", 1)
            self._gauge_conns()
            threading.Thread(
                target=self._conn_loop,
                args=(cid, conn),
                name=f"ingest-conn-{cid}",
                daemon=True,
            ).start()

    def _conn_loop(self, cid: int, conn: _Conn) -> None:
        reader = FrameReader(self.max_frame)
        try:
            while conn.open:
                try:
                    data = conn.sock.recv(65536)
                except OSError:
                    break
                if not data:
                    if reader.pending_bytes():
                        raise TruncatedStream(
                            f"{reader.pending_bytes()} bytes"
                        )
                    break
                self._bump("bytes_in", len(data))
                if self.metrics is not None:
                    self.metrics.record(
                        "ingest_bytes_total", len(data), direction="in"
                    )
                for frame in reader.feed(data):
                    self._dispatch(conn, frame)
        except ProtocolError as e:
            self._shed(conn, e)
        except Exception:
            self._close_conn(conn)
        finally:
            with self._lock:
                self._conns.pop(cid, None)
            self._close_conn(conn)
            self._gauge_conns()

    def _dispatch(self, conn: _Conn, frame: Frame) -> None:
        if frame.ftype == FRAME_PING:
            self._send(
                conn, encode_frame(FRAME_PONG, frame.request_id)
            )
            return
        if frame.ftype not in REQUEST_PLANES:
            # a response/error frame arriving at the listener is a
            # confused peer — shed it
            raise BadFrameType(f"0x{frame.ftype:02x} at listener")
        self._bump("frames_total")
        if self.metrics is not None:
            self.metrics.record(
                "ingest_frames_total", 1,
                plane=REQUEST_PLANES[frame.ftype],
            )
        # flow control: block the reader (and, through TCP, the peer)
        # once this connection has max_inflight frames being served
        with conn.cv:
            while conn.inflight >= self.max_inflight and conn.open:
                conn.cv.wait(timeout=1.0)
            if not conn.open:
                return
            conn.inflight += 1
        self._pool.submit(self._serve_one, conn, frame)

    # -- serve / write -------------------------------------------------------

    def _serve_one(self, conn: _Conn, frame: Frame) -> None:
        try:
            try:
                status, payload = self.frame_handler(frame)
            except Exception as e:  # app error == HTTP 500, not a shed
                status, payload = 500, json.dumps(
                    {"error": str(e)}
                ).encode("utf-8")
            self._send(
                conn,
                encode_frame(
                    FRAME_RESPONSE, frame.request_id, payload,
                    budget=status, flags=0,
                ),
            )
        finally:
            with conn.cv:
                conn.inflight -= 1
                conn.cv.notify()

    def _send(self, conn: _Conn, data: bytes) -> None:
        try:
            with conn.wlock:
                conn.sock.sendall(data)
            self._bump("bytes_out", len(data))
            if self.metrics is not None:
                self.metrics.record(
                    "ingest_bytes_total", len(data), direction="out"
                )
        except OSError:
            self._close_conn(conn)

    def _shed(self, conn: _Conn, exc: ProtocolError) -> None:
        self._bump("protocol_errors_total")
        self._bump("shed_connections")
        if self.metrics is not None:
            self.metrics.record(
                "ingest_protocol_errors_total", 1, code=exc.code
            )
        try:  # best-effort error frame; the peer may already be gone
            self._send(
                conn,
                encode_frame(
                    FRAME_ERROR, 0,
                    json.dumps({"error": exc.code}).encode("utf-8"),
                    budget=400, flags=0,
                ),
            )
        except Exception:
            pass
        self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        with conn.cv:
            conn.open = False
            conn.cv.notify_all()
        # shutdown BEFORE close: close() alone leaves the kernel file
        # description alive while the reader thread is blocked in
        # recv() on it, so no FIN ever reaches the peer and the
        # connection leaks on both sides
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass


class StreamClient:
    """One multiplexed connection to a StreamListener. `submit()`
    returns a Future resolving to (status, payload bytes); a reader
    thread correlates responses by request id. Used by the bench
    lane, the soak harness's framed transport, and the tests."""

    def __init__(
        self,
        host: str,
        port: int,
        max_frame: int = DEFAULT_MAX_FRAME,
        connect_timeout: float = 10.0,
    ):
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._sock.settimeout(None)
        try:
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        except OSError:
            pass
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._next_id = 1
        self._closed = False
        self._reader = FrameReader(max_frame)
        self._thread = threading.Thread(
            target=self._read_loop, name="ingest-client", daemon=True
        )
        self._thread.start()

    def submit(
        self,
        payload: bytes,
        plane: int = PLANE_VALIDATE,
        budget_ms: int = 0,
    ) -> "Future[Tuple[int, bytes]]":
        fut: Future = Future()
        with self._plock:
            if self._closed:
                raise ConnectionError("stream client closed")
            rid = self._next_id
            self._next_id += 1
            self._pending[rid] = fut
        data = encode_frame(plane, rid, payload, budget=budget_ms)
        try:
            with self._wlock:
                self._sock.sendall(data)
        except OSError as e:
            with self._plock:
                self._pending.pop(rid, None)
            raise ConnectionError(str(e))
        return fut

    def request(
        self,
        payload: bytes,
        plane: int = PLANE_VALIDATE,
        budget_ms: int = 0,
        timeout: Optional[float] = 30.0,
    ) -> Tuple[int, bytes]:
        return self.submit(payload, plane, budget_ms).result(timeout)

    def _read_loop(self) -> None:
        try:
            while True:
                data = self._sock.recv(65536)
                if not data:
                    raise ConnectionError("stream closed by peer")
                for frame in self._reader.feed(data):
                    if frame.ftype == FRAME_PONG:
                        continue
                    if frame.ftype == FRAME_ERROR and frame.request_id == 0:
                        raise ProtocolError(
                            bytes(frame.payload).decode(
                                "utf-8", "replace"
                            )
                        )
                    with self._plock:
                        fut = self._pending.pop(frame.request_id, None)
                    if fut is not None:
                        fut.set_result(
                            (frame.budget, bytes(frame.payload))
                        )
        except Exception as e:
            self._fail_all(e)

    def _fail_all(self, exc: Exception) -> None:
        with self._plock:
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(
                    exc if isinstance(exc, Exception) else
                    ConnectionError(str(exc))
                )
        # shutdown first: it wakes the reader thread blocked in recv()
        # and pushes the FIN out; a bare close() would leave the kernel
        # file description pinned by that blocked recv, silently
        # leaking the server-side connection
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        self._fail_all(ConnectionError("stream client closed"))
        self._thread.join(timeout=1.0)

    def __enter__(self) -> "StreamClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
