"""Schema-aware zero-copy AdmissionReview decode (docs/ingest.md).

The legacy front door pays `json.loads` → full dict tree → a second
full `flatten_leaves` walk per request before the encoder ever sees a
token row. This module walks the wire bytes ONCE: an incremental
recursive-descent scanner over a `memoryview` of the frame payload
that

  * builds the small request envelope (uid / kind / namespace /
    operation / userInfo / ...) as plain Python values — the fields
    every handler, exclusion check, and decision record reads;
  * emits the encoder's token rows `(schema_path, idx0, idx1, kind,
    raw, num)` for `request.object` / `request.oldObject` DIRECTLY
    during the scan, bit-for-bit what `flatten_leaves` would yield
    (same `esc_seg` escaping, same "#" array marker, same two-level
    index lift with saturation, same empty-object/array kinds);
  * lifts the feature-bearing subtrees the match kernel needs
    (`apiVersion`, `kind`, `metadata` — labels live there) into real
    dicts, and defers everything else (`spec`, `status`, `data`, ...)
    behind a `LazyObject`: a dict subclass that materializes from the
    retained wire bytes only when a cold path (host interpreter,
    shadow oracle, external-data key extraction) actually reaches in.

Fallback semantics are the contract that keeps verdicts byte-identical
to the dict path: ANY schema surprise — duplicate keys (json.loads
keeps the last one; rows would double), NaN/Infinity literals, lone
structural garbage, numeric overflow, invalid UTF-8 — raises
`DecodeSurprise` and the caller re-parses with plain `json.loads`
(route "fallback", counted in `ingest_decode_fallback_total`). The
scanner is deliberately STRICTER than json.loads: everything it
accepts it decodes identically, everything it is unsure about it
hands back.
"""

from __future__ import annotations

import json as _json
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..flatten.encoder import (
    K_BOOL,
    K_EMPTY_ARR,
    K_EMPTY_OBJ,
    K_NULL,
    K_NUM,
    K_STR,
    esc_seg,
)

__all__ = [
    "DecodeSurprise",
    "LazyObject",
    "decode_review",
    "scan_review",
]

# object-subtree keys parsed into real values during the scan: the
# match-feature encoder reads gvk + metadata.labels on every review,
# so these must never trigger a materialization
LIFTED_KEYS = frozenset(("apiVersion", "kind", "metadata"))

# rows: (schema_path, idx0, idx1, kind, raw_value, num_value) —
# exactly flatten_leaves' tuple shape, relative to the subtree root
Row = Tuple[str, int, int, int, Optional[Any], float]


class DecodeSurprise(Exception):
    """The scanner met wire bytes it will not vouch for. Reason slugs
    land in `ingest_decode_fallback_total{reason=}`."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class LazyObject(dict):
    """`request.object` decoded at wire speed: a REAL dict (isinstance
    checks all over the engine keep working) whose storage holds only
    the lifted subtrees, plus the scanned token rows and the raw wire
    bytes. Key listing / membership answers from the scanned key list
    without parsing; the first access to a deferred value re-parses
    the retained bytes with `json.loads` (identical semantics) and
    completes the storage in wire order. Any MUTATION (the mutation
    plane's patches, test scaffolding) forces materialization and
    drops the rows — stale rows can never reach the encoder."""

    __slots__ = ("_keys", "_preflat_rows", "_raw", "_mat", "_on_mat")

    def __init__(
        self,
        lifted: Dict[str, Any],
        keys: Tuple[str, ...],
        rows: List[Row],
        raw,
        on_materialize: Optional[Callable[[], None]] = None,
    ):
        super().__init__(lifted)
        self._keys: Optional[Tuple[str, ...]] = keys
        self._preflat_rows: Optional[List[Row]] = rows
        self._raw = raw
        self._mat = False
        self._on_mat = on_materialize

    # -- row emission (the flatten/encoder.py row-emit entry point) ----------

    def token_rows(self) -> Optional[List[Row]]:
        """The scanned leaf rows (flatten_leaves shape, subtree-
        relative), or None once a mutation invalidated them."""
        return self._preflat_rows

    # -- lazy materialization ------------------------------------------------

    def _materialize(self) -> None:
        if self._mat:
            return
        self._mat = True
        full = _json.loads(bytes(self._raw))
        # rebuild storage in WIRE order (lifted keys alone would leave
        # deferred keys appended at the end and change row order for
        # any later re-flatten)
        dict.clear(self)
        dict.update(self, full)
        if self._on_mat is not None:
            try:
                self._on_mat()
            except Exception:
                pass  # counters must never break an admission

    def _force(self) -> None:
        """Materialize AND invalidate: a caller is about to mutate."""
        self._materialize()
        self._preflat_rows = None
        self._keys = None

    # -- reads ---------------------------------------------------------------

    def __getitem__(self, k):
        try:
            return dict.__getitem__(self, k)
        except KeyError:
            if not self._mat and self._keys is not None and k in self._keys:
                self._materialize()
                return dict.__getitem__(self, k)
            raise

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __contains__(self, k) -> bool:
        if self._keys is not None:
            return k in self._keys
        return dict.__contains__(self, k)

    def __iter__(self):
        if self._keys is not None:
            return iter(self._keys)
        return dict.__iter__(self)

    def __len__(self) -> int:
        if self._keys is not None:
            return len(self._keys)
        return dict.__len__(self)

    def keys(self):
        self._materialize()
        return dict.keys(self)

    def values(self):
        self._materialize()
        return dict.values(self)

    def items(self):
        self._materialize()
        return dict.items(self)

    def __eq__(self, other):
        # the hot-path probe is `obj != {}` (encode_review_features);
        # a LazyObject is non-empty by construction, so emptiness
        # never needs the bytes
        if isinstance(other, dict) and len(other) == 0:
            return len(self) == 0
        self._materialize()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    __hash__ = None  # dicts are unhashable; keep it that way

    def copy(self):
        self._materialize()
        return dict(dict.items(self))

    def __reduce__(self):
        # deepcopy/pickle walk C-level storage; hand them a plain,
        # fully-parsed dict instead
        self._materialize()
        return (dict, (dict(dict.items(self)),))

    def __repr__(self):
        if self._mat:
            return dict.__repr__(self)
        return (
            f"LazyObject(keys={list(self._keys or ())!r}, "
            f"lifted={sorted(dict.keys(self))!r})"
        )

    # -- mutations: materialize first, rows die ------------------------------

    def __setitem__(self, k, v):
        self._force()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._force()
        dict.__delitem__(self, k)

    def setdefault(self, k, default=None):
        self._force()
        return dict.setdefault(self, k, default)

    def update(self, *args, **kwargs):
        self._force()
        dict.update(self, *args, **kwargs)

    def pop(self, *args):
        self._force()
        return dict.pop(self, *args)

    def popitem(self):
        self._force()
        return dict.popitem(self)

    def clear(self):
        self._mat = True
        self._preflat_rows = None
        self._keys = None
        dict.clear(self)


# ---------------------------------------------------------------------------
# the scanner

_NUM_RE = re.compile(rb"-?(?:0|[1-9][0-9]*)(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?")
_PLAIN_RE = re.compile(rb'[^"\\\x00-\x1f]*')
_CTRL_RE = re.compile(rb"[\x00-\x1f]")
_HEX4_RE = re.compile(rb"[0-9a-fA-F]{4}")
_ESCAPES = {
    0x22: '"', 0x5C: "\\", 0x2F: "/", 0x62: "\b",
    0x66: "\f", 0x6E: "\n", 0x72: "\r", 0x74: "\t",
}


class _Scanner:
    __slots__ = ("data", "mv", "i", "n", "on_materialize")

    def __init__(
        self,
        data: bytes,
        start: int = 0,
        end: Optional[int] = None,
        on_materialize: Optional[Callable[[], None]] = None,
    ):
        self.data = data
        self.mv = memoryview(data)
        self.i = start
        self.n = len(data) if end is None else end
        self.on_materialize = on_materialize

    # -- lexical helpers -----------------------------------------------------

    def _ws(self) -> None:
        data, n, i = self.data, self.n, self.i
        while i < n and data[i] in (0x20, 0x09, 0x0A, 0x0D):
            i += 1
        self.i = i

    def _string(self) -> str:
        """Decode a JSON string; self.i is at the opening quote."""
        data, n = self.data, self.n
        i = self.i + 1
        j = data.find(b'"', i, n)
        if j < 0:
            raise DecodeSurprise("truncated_string")
        if data.find(b"\\", i, j) < 0:
            # no escapes before the first quote: the common case
            if _CTRL_RE.search(data, i, j):
                raise DecodeSurprise("control_char")
            try:
                s = str(self.mv[i:j], "utf-8")
            except UnicodeDecodeError:
                raise DecodeSurprise("bad_utf8")
            self.i = j + 1
            return s
        parts: List[str] = []
        while True:
            m = _PLAIN_RE.match(data, i, n)
            j = m.end()
            if j > i:
                try:
                    parts.append(str(self.mv[i:j], "utf-8"))
                except UnicodeDecodeError:
                    raise DecodeSurprise("bad_utf8")
            if j >= n:
                raise DecodeSurprise("truncated_string")
            c = data[j]
            if c == 0x22:
                self.i = j + 1
                return "".join(parts)
            if c != 0x5C:
                raise DecodeSurprise("control_char")
            if j + 1 >= n:
                raise DecodeSurprise("truncated_string")
            e = data[j + 1]
            if e == 0x75:  # \uXXXX (surrogate pairs combined, lone kept
                # — exactly json.loads' behavior)
                if j + 6 > n or _HEX4_RE.match(data, j + 2, j + 6) is None:
                    raise DecodeSurprise("bad_unicode_escape")
                cu = int(data[j + 2:j + 6], 16)
                i = j + 6
                if 0xD800 <= cu <= 0xDBFF and data.startswith(b"\\u", i):
                    if i + 6 <= n and _HEX4_RE.match(data, i + 2, i + 6):
                        lo = int(data[i + 2:i + 6], 16)
                        if 0xDC00 <= lo <= 0xDFFF:
                            cu = 0x10000 + ((cu - 0xD800) << 10) + (
                                lo - 0xDC00
                            )
                            i += 6
                parts.append(chr(cu))
            else:
                ch = _ESCAPES.get(e)
                if ch is None:
                    raise DecodeSurprise("bad_escape")
                parts.append(ch)
                i = j + 2

    def _expect(self, byte: int, reason: str) -> None:
        if self.i >= self.n or self.data[self.i] != byte:
            raise DecodeSurprise(reason)
        self.i += 1

    # -- the one recursive value walker --------------------------------------
    #
    # build=True constructs the Python value (json.loads-identical);
    # emit=True appends flatten_leaves-identical rows. The zero-copy
    # win is build=False, emit=True: deep subtrees never become dicts.

    def _value(
        self,
        build: bool,
        emit: bool,
        path: Optional[List[str]],
        i0: int,
        i1: int,
        rows: Optional[List[Row]],
    ):
        self._ws()
        data, n = self.data, self.n
        i = self.i
        if i >= n:
            raise DecodeSurprise("truncated")
        c = data[i]
        if c == 0x7B:  # {
            self.i = i + 1
            self._ws()
            obj: Optional[Dict[str, Any]] = {} if build else None
            if self.i < n and data[self.i] == 0x7D:
                self.i += 1
                if emit:
                    rows.append(
                        (".".join(path), i0, i1, K_EMPTY_OBJ, None, 0.0)
                    )
                return obj
            seen = set()
            while True:
                self._ws()
                if self.i >= n or data[self.i] != 0x22:
                    raise DecodeSurprise("bad_key")
                k = self._string()
                if k in seen:
                    # json.loads keeps the LAST duplicate; a single
                    # scan would emit rows for both — bail out
                    raise DecodeSurprise("dup_key")
                seen.add(k)
                self._ws()
                self._expect(0x3A, "bad_colon")
                if emit:
                    path.append(esc_seg(k))
                    v = self._value(build, True, path, i0, i1, rows)
                    path.pop()
                else:
                    v = self._value(build, False, path, i0, i1, rows)
                if build:
                    obj[k] = v
                self._ws()
                if self.i >= n:
                    raise DecodeSurprise("truncated")
                c2 = data[self.i]
                self.i += 1
                if c2 == 0x2C:
                    continue
                if c2 == 0x7D:
                    return obj
                raise DecodeSurprise("bad_object_sep")
        if c == 0x5B:  # [
            self.i = i + 1
            self._ws()
            arr: Optional[List[Any]] = [] if build else None
            if self.i < n and data[self.i] == 0x5D:
                self.i += 1
                if emit:
                    rows.append(
                        (".".join(path), i0, i1, K_EMPTY_ARR, None, 0.0)
                    )
                return arr
            if emit:
                path.append("#")
            j = 0
            while True:
                if emit:
                    # flatten_leaves' two-level index lift: indices
                    # past the second array level saturate
                    if i0 < 0:
                        a, b = j, -1
                    elif i1 < 0:
                        a, b = i0, j
                    else:
                        a, b = i0, i1
                else:
                    a, b = i0, i1
                v = self._value(build, emit, path, a, b, rows)
                if build:
                    arr.append(v)
                j += 1
                self._ws()
                if self.i >= n:
                    raise DecodeSurprise("truncated")
                c2 = data[self.i]
                self.i += 1
                if c2 == 0x2C:
                    continue
                if c2 == 0x5D:
                    break
                raise DecodeSurprise("bad_array_sep")
            if emit:
                path.pop()
            return arr
        if c == 0x22:
            s = self._string()
            if emit:
                rows.append((".".join(path), i0, i1, K_STR, s, 0.0))
            return s
        if c == 0x74:  # t
            if data.startswith(b"true", i):
                self.i = i + 4
                if emit:
                    rows.append((".".join(path), i0, i1, K_BOOL, True, 1.0))
                return True
            raise DecodeSurprise("bad_literal")
        if c == 0x66:  # f
            if data.startswith(b"false", i):
                self.i = i + 5
                if emit:
                    rows.append(
                        (".".join(path), i0, i1, K_BOOL, False, 0.0)
                    )
                return False
            raise DecodeSurprise("bad_literal")
        if c == 0x6E:  # n
            if data.startswith(b"null", i):
                self.i = i + 4
                if emit:
                    rows.append((".".join(path), i0, i1, K_NULL, None, 0.0))
                return None
            raise DecodeSurprise("bad_literal")
        m = _NUM_RE.match(data, i, n)
        if m is None or m.end() == i:
            # NaN/Infinity land here too: json.loads accepts them,
            # the rows could not represent them — fall back
            raise DecodeSurprise("bad_value")
        j2 = m.end()
        tb = data[i:j2]
        self.i = j2
        if b"." in tb or b"e" in tb or b"E" in tb:
            v: Any = float(tb)
        else:
            v = int(tb)
        if emit:
            try:
                num = float(v)
            except OverflowError:
                # flatten_leaves would raise at encode time; the dict
                # path must own that failure, not the scanner
                raise DecodeSurprise("num_overflow")
            rows.append((".".join(path), i0, i1, K_NUM, v, num))
        return v

    # -- AdmissionReview-shaped entry points ---------------------------------

    def _admission_object(self):
        """`request.object` / `request.oldObject`: the zero-copy
        subtree. Non-dict values (null, a scalar) and `{}` build
        normally; a non-empty dict becomes a LazyObject."""
        self._ws()
        if self.i >= self.n or self.data[self.i] != 0x7B:
            return self._value(True, False, None, -1, -1, None)
        start = self.i
        data, n = self.data, self.n
        self.i += 1
        self._ws()
        if self.i < n and data[self.i] == 0x7D:
            self.i += 1
            return {}
        rows: List[Row] = []
        lifted: Dict[str, Any] = {}
        keys: List[str] = []
        path: List[str] = []
        while True:
            self._ws()
            if self.i >= n or data[self.i] != 0x22:
                raise DecodeSurprise("bad_key")
            k = self._string()
            if k in keys:
                raise DecodeSurprise("dup_key")
            keys.append(k)
            self._ws()
            self._expect(0x3A, "bad_colon")
            path.append(esc_seg(k))
            if k in LIFTED_KEYS:
                lifted[k] = self._value(True, True, path, -1, -1, rows)
            else:
                self._value(False, True, path, -1, -1, rows)
            path.pop()
            self._ws()
            if self.i >= n:
                raise DecodeSurprise("truncated")
            c2 = data[self.i]
            self.i += 1
            if c2 == 0x2C:
                continue
            if c2 == 0x7D:
                break
            raise DecodeSurprise("bad_object_sep")
        raw = self.mv[start:self.i]
        return LazyObject(
            lifted, tuple(keys), rows, raw, self.on_materialize
        )

    def _special_object(self, level: str) -> Dict[str, Any]:
        """A built dict whose named keys route specially: the review's
        `request`, the request's `object`/`oldObject`."""
        self._expect(0x7B, "bad_object")
        out: Dict[str, Any] = {}
        data, n = self.data, self.n
        self._ws()
        if self.i < n and data[self.i] == 0x7D:
            self.i += 1
            return out
        while True:
            self._ws()
            if self.i >= n or data[self.i] != 0x22:
                raise DecodeSurprise("bad_key")
            k = self._string()
            if k in out:
                raise DecodeSurprise("dup_key")
            self._ws()
            self._expect(0x3A, "bad_colon")
            if level == "review" and k == "request":
                self._ws()
                if self.i < n and data[self.i] == 0x7B:
                    v: Any = self._special_object("request")
                else:
                    v = self._value(True, False, None, -1, -1, None)
            elif level == "request" and k in ("object", "oldObject"):
                v = self._admission_object()
            else:
                v = self._value(True, False, None, -1, -1, None)
            out[k] = v
            self._ws()
            if self.i >= n:
                raise DecodeSurprise("truncated")
            c2 = data[self.i]
            self.i += 1
            if c2 == 0x2C:
                continue
            if c2 == 0x7D:
                return out
            raise DecodeSurprise("bad_object_sep")

    def parse(self) -> Dict[str, Any]:
        self._ws()
        if self.i >= self.n or self.data[self.i] != 0x7B:
            raise DecodeSurprise("top_not_object")
        review = self._special_object("review")
        self._ws()
        if self.i != self.n:
            raise DecodeSurprise("trailing_data")
        return review


def scan_review(
    payload,
    on_materialize: Optional[Callable[[], None]] = None,
) -> Dict[str, Any]:
    """One-pass AdmissionReview scan. `payload` is bytes or any
    buffer; raises DecodeSurprise when the wire bytes need the
    json.loads path."""
    data = payload if isinstance(payload, bytes) else bytes(payload)
    try:
        return _Scanner(data, on_materialize=on_materialize).parse()
    except DecodeSurprise:
        raise
    except (UnicodeDecodeError, RecursionError, OverflowError) as e:
        raise DecodeSurprise(type(e).__name__.lower())


def decode_review(
    payload,
    zerocopy: bool = True,
    on_materialize: Optional[Callable[[], None]] = None,
) -> Tuple[Any, str, Optional[str]]:
    """(review, route, fallback_reason). Routes: "zerocopy" (scanner
    rows), "fallback" (scanner declined, json.loads answered),
    "legacy" (scanner not attempted). A payload json.loads itself
    rejects raises here exactly like the legacy HTTP body path."""
    data = payload if isinstance(payload, bytes) else bytes(payload)
    if not zerocopy:
        return _json.loads(data), "legacy", None
    try:
        return scan_review(data, on_materialize=on_materialize), (
            "zerocopy"
        ), None
    except DecodeSurprise as e:
        return _json.loads(data), "fallback", e.reason
