"""IngestServer: frames in, verdict envelopes out (docs/ingest.md).

Glue between the framed transport and the existing admission planes.
Each request frame is served on the listener's worker pool:

    decode (zero-copy scanner, json.loads fallback)
      -> ingest_decode span recorded into the request's trace
      -> decision facts stamped (decode_route, bytes_on_wire)
      -> the SAME synchronous handler the legacy HTTP path calls
         (BatchedValidationHandler.handle -> MicroBatcher.submit ->
          AdmissionScheduler.offer) with the frame's deadline budget
      -> review_envelope JSON back in a response frame

Routing through the identical handler objects is what makes framed
verdicts byte-identical to legacy HTTP ones — the transport and the
decoder are the only things that change. Zero-copy decode applies to
validation frames only: the mutation plane rewrites `request.object`,
so its frames take the plain `json.loads` route (route "legacy"), as
do agent and namespace-label frames (tiny envelopes, nothing to lift).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..obs import derive_trace_id
from .decode import decode_review
from .transport import (
    DEFAULT_MAX_FRAME,
    DEFAULT_MAX_INFLIGHT,
    FLAG_DEADLINE,
    Frame,
    PLANE_AGENT,
    PLANE_LABEL,
    PLANE_MUTATE,
    PLANE_VALIDATE,
    StreamListener,
)

__all__ = ["IngestServer"]


class IngestServer:
    """Framed-stream front door for one WebhookServer. Owns a
    StreamListener; serves frames through the webhook's own handler
    objects. Rollback is `--ingest off`: nothing here is load-bearing
    for the legacy HTTP path."""

    def __init__(
        self,
        webhook,
        host: str = "127.0.0.1",
        port: int = 0,
        max_frame: int = DEFAULT_MAX_FRAME,
        max_inflight: int = DEFAULT_MAX_INFLIGHT,
        workers: int = 64,
        decode: str = "zerocopy",  # "zerocopy" | "json"
        metrics=None,
        tracer=None,
        decision_log=None,
    ):
        self.webhook = webhook
        self.decode = decode
        self.metrics = metrics
        self.tracer = tracer
        self.decision_log = decision_log
        self._dlock = threading.Lock()
        self._decode_stats = {
            "zerocopy": 0, "fallback": 0, "legacy": 0, "materialized": 0,
        }
        self.listener = StreamListener(
            self._serve_frame,
            host=host,
            port=port,
            max_frame=max_frame,
            max_inflight=max_inflight,
            workers=workers,
            metrics=metrics,
        )
        self.port = self.listener.port

    def start(self) -> None:
        self.listener.start()

    def stop_accepting(self) -> None:
        self.listener.stop_accepting()

    def close(self) -> None:
        self.listener.close()

    def stats(self) -> Dict[str, Any]:
        with self._dlock:
            decode = dict(self._decode_stats)
        out = self.listener.stats()
        out["decode"] = decode
        out["port"] = self.port
        return out

    # -- per-frame serve path (listener worker pool) -------------------------

    def _count_decode(self, key: str) -> None:
        with self._dlock:
            self._decode_stats[key] += 1

    def _on_materialize(self) -> None:
        self._count_decode("materialized")
        if self.metrics is not None:
            self.metrics.record("ingest_lazy_materialize_total", 1)

    def _serve_frame(self, frame: Frame) -> Tuple[int, bytes]:
        webhook = self.webhook
        # the webhook's in-flight accounting covers framed admissions
        # too: stop() waits for accepted frames before the batchers die
        with webhook._inflight_cv:
            webhook._inflight += 1
        try:
            return self._serve_locked(frame)
        finally:
            with webhook._inflight_cv:
                webhook._inflight -= 1
                webhook._inflight_cv.notify_all()

    def _serve_locked(self, frame: Frame) -> Tuple[int, bytes]:
        from ..webhook.server import review_envelope

        webhook = self.webhook
        nbytes = len(frame.payload)
        zerocopy = (
            self.decode == "zerocopy" and frame.ftype == PLANE_VALIDATE
        )
        wall0 = time.time()
        t0 = time.perf_counter()
        try:
            review, route, reason = decode_review(
                frame.payload,
                zerocopy=zerocopy,
                on_materialize=self._on_materialize,
            )
        except Exception as e:
            # json.loads itself rejected the payload: same 500-shaped
            # answer the legacy HTTP body path gives
            if self.metrics is not None:
                self.metrics.record(
                    "ingest_decode_fallback_total", 1, reason="unparseable"
                )
            return 500, json.dumps({"error": str(e)}).encode("utf-8")
        dt = time.perf_counter() - t0
        self._count_decode(route)
        if self.metrics is not None:
            self.metrics.observe(
                "ingest_decode_seconds", dt, route=route
            )
            if route == "fallback":
                self.metrics.record(
                    "ingest_decode_fallback_total", 1,
                    reason=reason or "unknown",
                )
        if not isinstance(review, dict):
            return 500, json.dumps(
                {"error": "AdmissionReview payload is not an object"}
            ).encode("utf-8")
        request = review.get("request") or {}
        trace_id = derive_trace_id(request.get("uid"))
        if self.tracer is not None and trace_id is not None:
            # lands next to device_execute in the request's trace: the
            # handler's root span below shares the same trace id
            self.tracer.record_span(
                "ingest_decode", wall0, wall0 + dt,
                trace_id=trace_id,
                route=route,
                bytes_on_wire=nbytes,
            )
        if self.decision_log is not None and trace_id is not None:
            self.decision_log.note_dispatch(
                trace_id, decode_route=route, bytes_on_wire=nbytes
            )
        deadline: Optional[float] = None
        if frame.flags & FLAG_DEADLINE and frame.budget > 0:
            deadline = time.monotonic() + frame.budget / 1000.0
        try:
            if frame.ftype == PLANE_LABEL:
                resp = webhook.label_handler.handle(request)
            elif frame.ftype == PLANE_MUTATE:
                if webhook.mutation_handler is None:
                    return 404, json.dumps(
                        {"error": "mutation not enabled"}
                    ).encode("utf-8")
                resp = webhook.mutation_handler.handle(
                    request, trace_id=trace_id
                )
            elif frame.ftype == PLANE_AGENT:
                if webhook.agent_handler is None:
                    return 404, json.dumps(
                        {"error": "agent review not enabled"}
                    ).encode("utf-8")
                resp = webhook.agent_handler.handle(
                    request, trace_id=trace_id
                )
            else:
                with webhook.handler.deadline_scope(deadline):
                    resp = webhook.handler.handle(
                        request, trace_id=trace_id
                    )
        except Exception as e:
            return 500, json.dumps({"error": str(e)}).encode("utf-8")
        payload = json.dumps(
            review_envelope(review, request, resp, trace_id=trace_id)
        ).encode("utf-8")
        return 200, payload
