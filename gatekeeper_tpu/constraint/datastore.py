"""In-memory hierarchical data store for driver base documents.

The CPU equivalent of the reference driver's OPA inmem storage usage
(vendor/.../frameworks/constraint/pkg/client/drivers/local/local.go:241-300):
slash-separated paths, parent auto-creation on write, and conflict errors
when a write descends through a non-object (local.go:248-273 checks path
conflicts via storage.MakeDir semantics).
"""

from __future__ import annotations

import copy
import json
from typing import Any, List, Optional, Tuple, Union

PathLike = Union[str, List[str]]


class PathConflictError(Exception):
    """Write path traverses an existing non-object value."""


_HEX = set("0123456789abcdefABCDEF")


def _path_unescape(seg: str) -> str:
    """Go url.PathUnescape: %XX decoded ("+" untouched); any malformed
    escape errors, in which case ParsePathEscaped keeps the segment as-is
    (opa/storage/path.go:35-46)."""
    if "%" not in seg:
        return seg
    i = seg.find("%")
    while i != -1:
        if len(seg) - i < 3 or seg[i + 1] not in _HEX or seg[i + 2] not in _HEX:
            return seg  # malformed escape: keep original
        i = seg.find("%", i + 3)
    from urllib.parse import unquote

    return unquote(seg)


def parse_path(path: PathLike) -> List[str]:
    """storage.ParsePathEscaped (local.go:233-239): split on "/", then
    URL-unescape each segment — data keys hold the unescaped form (e.g.
    groupVersion "extensions/v1beta1"), the escaping exists only in the
    path-string transport."""
    if isinstance(path, str):
        return [_path_unescape(seg) for seg in path.split("/") if seg != ""]
    return list(path)


class DataStore:
    """A dict tree addressed by /seg/seg/... paths."""

    def __init__(self):
        self._root: dict = {}

    def put(self, path: PathLike, value: Any) -> None:
        segs = parse_path(path)
        if not segs:
            if not isinstance(value, dict):
                raise PathConflictError("root document must be an object")
            self._root = copy.deepcopy(value)
            return
        node = self._root
        for seg in segs[:-1]:
            if seg not in node:
                node[seg] = {}
            child = node[seg]
            if not isinstance(child, dict):
                # stored None leaves conflict too — absence is keyed on the
                # dict, not the value
                raise PathConflictError(
                    f"path segment {seg!r} is a leaf, cannot descend"
                )
            node = child
        node[segs[-1]] = copy.deepcopy(value)

    def delete(self, path: PathLike) -> bool:
        """Remove the subtree at path. Returns False if it did not exist."""
        segs = parse_path(path)
        if not segs:
            existed = bool(self._root)
            self._root = {}
            return existed
        node = self._root
        for seg in segs[:-1]:
            child = node.get(seg)
            if not isinstance(child, dict):
                return False
            node = child
        if segs[-1] not in node:
            return False
        del node[segs[-1]]
        return True

    def get(self, path: PathLike, default: Any = None) -> Any:
        node: Any = self._root
        for seg in parse_path(path):
            if not isinstance(node, dict) or seg not in node:
                return default
            node = node[seg]
        return node

    def snapshot(self, path: PathLike = "") -> Any:
        return copy.deepcopy(self.get(path, {}))

    def dump_json(self) -> str:
        return json.dumps(self._root, sort_keys=True, indent=2, default=str)
