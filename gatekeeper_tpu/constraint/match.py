"""Native constraint-match semantics oracle.

Implements, in plain Python, exactly the predicate the reference installs as
an interpreted Rego library (pkg/target/target_template_source.go:6-387,
mounted via client/client.go:688-700): kind selectors, namespace /
excludedNamespaces, scope, labelSelector (with the UPDATE old/new OR-match),
namespaceSelector (resolved from `_unstable.namespace` or the synced
Namespace cache), and the `autoreject_review` rule.

This single implementation is the behavior contract shared by
  * the CPU driver (called per-review here), and
  * the vectorized TPU match kernel (gatekeeper_tpu/engine/match.py), which
    is differentially tested against this module.

Deliberately replicated quirks of the reference Rego (each covered by a test):
  * A review with NO namespace field (cluster-scoped admission request) that
    is not itself a Namespace trivially matches namespaces/excludedNamespaces/
    namespaceSelector (`always_match_ns_selectors`,
    target_template_source.go:311-314), and never autorejects: OPA's
    compiler hoists `input.review.namespace` out of the negated cache
    lookup in autoreject_review (:17), so an absent namespace fails the
    whole rule. Definedness of `input.review.kind` is likewise load-bearing
    through hoisted `is_ns(...)` operands.
  * matchExpressions `In`/`NotIn` with an empty `values` list never violate
    (the `count(values) > 0` guards at :190,:198), and unrecognized operators
    are silently ignored (no match_expression_violated clause applies).
  * A Namespace-kind review whose `object.metadata.name` is missing (e.g.
    DELETE reviews carrying only oldObject) fails `get_ns_name` (:301-309),
    so any constraint with `namespaces`/`excludedNamespaces` does not match.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from .hooks import (  # noqa: F401  (re-exported: the M.* legacy surface)
    _MISSING,
    constraint_match,
    constraint_parameters,
    constraint_spec,
    enforcement_action,
    get_default,
    hook_get_default,
)


# -- review field helpers ---------------------------------------------------


def _review_kind(review: Any) -> Any:
    """input.review.kind as a raw ref: _MISSING when absent.

    Definedness matters: every `is_ns(input.review.kind)` call site has the
    operand hoisted by OPA's compiler (rewriteDynamics — see
    gatekeeper_tpu/rego/rewrite.py), so a review with NO kind field fails
    both `is_ns(...)` and `not is_ns(...)` clauses.
    """
    if isinstance(review, dict) and "kind" in review:
        return review["kind"]
    return _MISSING


def is_ns(review: Any) -> bool:
    """is_ns(input.review.kind) — group=="" and kind=="Namespace" (:287-290)."""
    k = _review_kind(review)
    if not isinstance(k, dict):
        return False
    return k.get("group") == "" and k.get("kind") == "Namespace"


def _review_namespace(review: Any) -> Any:
    """input.review.namespace as a raw ref: _MISSING when absent."""
    if isinstance(review, dict) and "namespace" in review:
        return review["namespace"]
    return _MISSING


def always_match_ns_selectors(review: Any) -> bool:
    """Cluster-scoped non-Namespace reviews skip all ns selectors (:311-314).

    Undefined review.kind fails the hoisted `not is_ns(...)` operand, so the
    rule is undefined (False here).
    """
    if _review_kind(review) is _MISSING:
        return False
    ns = get_default(review, "namespace", "") if isinstance(review, dict) else ""
    return (not is_ns(review)) and ns == ""


def get_ns_name(review: Any) -> Any:
    """get_ns_name (:301-309). Returns _MISSING when undefined.

    Both clauses hoist `input.review.kind` into `is_ns`/`not is_ns`, so a
    missing kind makes the whole partial set undefined.
    """
    if _review_kind(review) is _MISSING:
        return _MISSING
    if is_ns(review):
        obj = review.get("object") if isinstance(review, dict) else None
        if isinstance(obj, dict):
            meta = obj.get("metadata")
            if isinstance(meta, dict) and "name" in meta:
                return meta["name"]
        return _MISSING
    return _review_namespace(review)


def get_ns_candidates(review: Any, ns_cache: Dict[str, Any]) -> List[Any]:
    """get_ns (:292-299): the namespace OBJECT(s) for the review.

    A partial set in the reference: clause 1 contributes `_unstable.namespace`
    whenever the field is defined (any value, null included); clause 2
    contributes the synced-cache object (data.external.<t>.cluster.v1.
    Namespace[review.namespace]) whenever `not _unstable.namespace` succeeds —
    i.e. the field is absent OR false. So a literal false value yields BOTH
    members, and matches_nsselector succeeds if ANY member matches.
    """
    out: List[Any] = []
    unstable_ns = _MISSING
    if isinstance(review, dict):
        unstable = review.get("_unstable")
        if isinstance(unstable, dict) and "namespace" in unstable:
            unstable_ns = unstable["namespace"]
    if unstable_ns is not _MISSING:
        out.append(unstable_ns)
    if unstable_ns is _MISSING or unstable_ns is False:
        cached = _cached_ns(review, ns_cache)
        if cached is not _MISSING:
            out.append(cached)
    return out


def _cached_ns(review: Any, ns_cache: Dict[str, Any]) -> Any:
    name = _review_namespace(review)
    if name is _MISSING or not isinstance(ns_cache, dict):
        return _MISSING
    if not isinstance(name, str) or name not in ns_cache:
        return _MISSING
    return ns_cache[name]


# -- label selector logic ---------------------------------------------------


from .hooks import rego_scalar_eq  # noqa: E402,F401  (legacy M.* surface)


def values_shape(values: Any):
    """Normalize matchExpressions `values` the way the Rego evaluates it:
    returns (count_positive, elems) where count_positive reflects
    `count(values) > 0` (None when count() errors — numbers/bools/null)
    and elems are the members `values[_]` can yield (strings iterate to
    nothing, dicts to their values)."""
    if isinstance(values, list):
        return len(values) > 0, values
    if isinstance(values, dict):
        return len(values) > 0, list(values.values())
    if isinstance(values, str):
        return len(values) > 0, []
    return None, []  # count(number/bool/null) is a builtin error


def match_expression_violated(
    operator: Any, labels: Dict[str, Any], key: Any, values: Any
) -> bool:
    """match_expression_violated (:184-210).

    has_field counts any present key — null included, since null is truthy
    in Rego (`object[field]` binds and succeeds). The `count(values) > 0`
    guards only gate In/NotIn; Exists/DoesNotExist ignore values entirely.
    """
    has_key = isinstance(labels, dict) and key in labels
    count_pos, elems = values_shape(values)
    if operator == "In":
        if not has_key:
            return True
        return bool(count_pos) and not any(
            rego_scalar_eq(labels[key], v) for v in elems
        )
    if operator == "NotIn":
        return (
            has_key
            and bool(count_pos)
            and any(rego_scalar_eq(labels[key], v) for v in elems)
        )
    if operator == "Exists":
        return not has_key
    if operator == "DoesNotExist":
        return has_key
    return False  # unknown operators contribute no violation


def matches_label_selector(selector: Any, labels: Any) -> bool:
    """matches_label_selector (:213-230)."""
    if not isinstance(labels, dict):
        labels = {}
    match_labels = get_default(selector, "matchLabels", {})
    if isinstance(match_labels, dict):
        for k, v in match_labels.items():
            if k not in labels or not rego_scalar_eq(labels[k], v):
                return False
    elif match_labels not in ([], ""):
        # non-object matchLabels: the satisfied-count comprehension yields
        # nothing while count(matchLabels) > 0 (or errors), so no match
        return False
    match_exprs = get_default(selector, "matchExpressions", [])
    if isinstance(match_exprs, list):
        for expr in match_exprs:
            if not isinstance(expr, dict):
                # expr["operator"] undefined -> comprehension body fails for
                # this element -> no violation recorded
                continue
            if "operator" not in expr or "key" not in expr:
                continue
            if match_expression_violated(
                expr["operator"],
                labels,
                expr["key"],
                get_default(expr, "values", []),
            ):
                return False
    return True


def _object_labels(obj: Any) -> Dict[str, Any]:
    metadata = get_default(obj, "metadata", {})
    labels = get_default(metadata, "labels", {})
    return labels if isinstance(labels, dict) else {}


def _review_obj(review: Any, field: str) -> Any:
    """get_default(review, field, {}) compared against {} (:233-281)."""
    val = get_default(review, field, {})
    return val


def any_labelselector_match(selector: Any, review: Any) -> bool:
    """any_labelselector_match (:233-281): OR over object/oldObject labels."""
    obj = _review_obj(review, "object")
    old = _review_obj(review, "oldObject")
    obj_absent = obj == {}
    old_absent = old == {}
    if old_absent and not obj_absent:
        return matches_label_selector(selector, _object_labels(obj))
    if not old_absent and obj_absent:
        return matches_label_selector(selector, _object_labels(old))
    if not old_absent and not obj_absent:
        return matches_label_selector(
            selector, _object_labels(obj)
        ) or matches_label_selector(selector, _object_labels(old))
    return matches_label_selector(selector, {})


# -- the five match dimensions ----------------------------------------------


def any_kind_selector_matches(match: Any, review: Any) -> bool:
    """Kind selector (:131-156)."""
    kind_selectors = get_default(
        match, "kinds", [{"apiGroups": ["*"], "kinds": ["*"]}]
    )
    if not isinstance(kind_selectors, list):
        return False
    k = _review_kind(review)
    if not isinstance(k, dict):
        k = {}
    group = k.get("group", _MISSING)
    kind = k.get("kind", _MISSING)
    for ks in kind_selectors:
        if not isinstance(ks, dict):
            continue
        groups = ks.get("apiGroups")
        kinds = ks.get("kinds")
        if not isinstance(groups, list) or not isinstance(kinds, list):
            # ks.apiGroups[_] over a missing/non-array field is undefined
            continue
        group_ok = "*" in groups or (group is not _MISSING and group in groups)
        kind_ok = "*" in kinds or (kind is not _MISSING and kind in kinds)
        if group_ok and kind_ok:
            return True
    return False


def matches_scope(match: Any, review: Any) -> bool:
    """Scope selector (:162-178).

    A present-but-null scope passes has_field (null is truthy in Rego) yet
    equals none of "*"/"Namespaced"/"Cluster", so nothing matches.
    """
    if not _has_field(match, "scope"):
        return True
    scope = match["scope"]
    if scope == "*":
        return True
    ns = get_default(review, "namespace", "")
    if scope == "Namespaced":
        return ns != ""
    if scope == "Cluster":
        return ns == ""
    return False


def matches_namespaces(match: Any, review: Any) -> bool:
    """namespaces (:316-332)."""
    if not _has_field(match, "namespaces"):
        return True
    if always_match_ns_selectors(review):
        return True
    ns = get_ns_name(review)
    if ns is _MISSING:
        return False
    nss = match["namespaces"]
    # Rego set membership, not Python `in` (True != 1 under Rego equality)
    return isinstance(nss, list) and any(rego_scalar_eq(ns, n) for n in nss)


def does_not_match_excludednamespaces(match: Any, review: Any) -> bool:
    """excludedNamespaces (:334-350)."""
    if not _has_field(match, "excludedNamespaces"):
        return True
    if always_match_ns_selectors(review):
        return True
    ns = get_ns_name(review)
    if ns is _MISSING:
        return False
    nss = match["excludedNamespaces"]
    if not isinstance(nss, list):
        # `{n | n = match.excludedNamespaces[_]}` over a non-array is the
        # empty set, so ns is trivially not excluded
        return True
    return not any(rego_scalar_eq(ns, n) for n in nss)


def matches_nsselector(
    match: Any, review: Any, ns_cache: Dict[str, Any]
) -> bool:
    """namespaceSelector (:352-386)."""
    if not _has_field(match, "namespaceSelector"):
        return True
    if always_match_ns_selectors(review):
        return True
    if _review_kind(review) is _MISSING:
        # both remaining clauses hoist input.review.kind into is_ns
        return False
    if is_ns(review):
        return any_labelselector_match(
            get_default(match, "namespaceSelector", {}), review
        )
    selector = get_default(match, "namespaceSelector", {})
    for ns in get_ns_candidates(review, ns_cache):
        metadata = get_default(ns, "metadata", {})
        nslabels = get_default(metadata, "labels", {})
        if matches_label_selector(selector, nslabels):
            return True
    return False


def _has_field(obj: Any, field: str) -> bool:
    """has_field (:92-105): any present key counts — false via the explicit
    `object[field] == false` clause, null because null is truthy in Rego."""
    return isinstance(obj, dict) and field in obj


def matches_constraint(
    constraint: Dict[str, Any], review: Any, ns_cache: Dict[str, Any]
) -> bool:
    """matching_constraints body (:27-44) for a single constraint."""
    return matches_match(constraint_match(constraint), review, ns_cache)


def matches_match(
    match: Any, review: Any, ns_cache: Dict[str, Any]
) -> bool:
    """matches_constraint over a pre-extracted match block — the entry
    point target handlers use after translating their own match schema
    into this module's field vocabulary (docs/targets.md)."""
    if not any_kind_selector_matches(match, review):
        return False
    if not matches_namespaces(match, review):
        return False
    if not does_not_match_excludednamespaces(match, review):
        return False
    if not matches_nsselector(match, review, ns_cache):
        return False
    if not matches_scope(match, review):
        return False
    label_selector = get_default(match, "labelSelector", {})
    return any_labelselector_match(label_selector, review)


def matching_constraints(
    constraints: Iterable[Dict[str, Any]],
    review: Any,
    ns_cache: Dict[str, Any],
) -> List[Dict[str, Any]]:
    return [c for c in constraints if matches_constraint(c, review, ns_cache)]


# -- autoreject -------------------------------------------------------------


def needs_ns_selector(constraint: Dict[str, Any]) -> bool:
    """The ONLY constraint-dependent clause of autoreject_review: the
    constraint declares a namespaceSelector. Exported separately so
    batched callers can factor autoreject as
    `needs_ns_selector(c) AND review_autorejects(r)` in O(R + C); any
    future per-constraint condition MUST be added here (and the batched
    device path in tpudriver._query_many_device revisited), never
    inlined into autoreject alone."""
    return match_needs_ns_selector(constraint_match(constraint))


def match_needs_ns_selector(match: Any) -> bool:
    """needs_ns_selector over a pre-extracted (translated) match block."""
    return _has_field(match, "namespaceSelector")


def autoreject(
    constraint: Dict[str, Any], review: Any, ns_cache: Dict[str, Any]
) -> bool:
    """autoreject_review (:12-25) for a single constraint.

    Fires when the constraint needs a namespaceSelector but the review's
    namespace is neither attached (`_unstable.namespace`) nor cached, and the
    namespace field is present and not the empty string. Presence is
    required because OPA hoists `input.review.namespace` out of the negated
    cache lookup (`not DataRoot...Namespace[input.review.namespace]`), so an
    absent field fails the whole rule — cluster-scoped reviews never
    autoreject.

    Factored as needs_ns_selector(constraint) AND
    review_autorejects(review, ns_cache).
    """
    return needs_ns_selector(constraint) and review_autorejects(
        review, ns_cache
    )


def review_autorejects(review: Any, ns_cache: Dict[str, Any]) -> bool:
    """The review-side (constraint-independent) half of autoreject."""
    ns_name = _review_namespace(review)
    if ns_name is _MISSING:
        return False
    # not DataRoot.cluster.v1.Namespace[input.review.namespace]
    if (
        isinstance(ns_name, str)
        and isinstance(ns_cache, dict)
        and ns_name in ns_cache
    ):
        return False
    # not input.review._unstable.namespace — succeeds only when the path is
    # absent or the value is false (null/0/"" are truthy in Rego)
    if isinstance(review, dict):
        unstable = review.get("_unstable")
        if isinstance(unstable, dict):
            val = unstable.get("namespace", _MISSING)
            if val is not _MISSING and val is not False:
                return False
    # not input.review.namespace == ""  (undefined namespace -> succeeds)
    if ns_name == "":
        return False
    return True


# -- audit cross-join -------------------------------------------------------


def make_group_version(api_version: str) -> Optional[Tuple[str, str]]:
    """make_group_version (:74-83). Data keys hold *unescaped*
    groupVersions (storage.ParsePathEscaped unescapes what
    target.go:73's url.PathEscape encoded), so "apps/v1" splits into
    ("apps", "v1"). The Rego `[group, version] := split(...)` destructure
    is undefined for 2+ slashes — mirrored as None (object skipped)."""
    if "/" in api_version:
        parts = api_version.split("/")
        if len(parts) != 2:
            return None
        return parts[0], parts[1]
    return "", api_version


def make_review(
    obj: Any, api_version: str, kind: str, name: str, namespace: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """make_review (:61-68) + add_field namespace for namespaced objects."""
    gv = make_group_version(api_version)
    if gv is None:
        return None
    group, version = gv
    review: Dict[str, Any] = {
        "kind": {"group": group, "version": version, "kind": kind},
        "name": name,
        "object": obj,
    }
    if namespace is not None:
        review["namespace"] = namespace
    return review


def iter_cached_reviews(external: Any):
    """matching_reviews_and_constraints data walk (:47-59): yields a review
    per cached object, namespaced tree first, then cluster tree."""
    if not isinstance(external, dict):
        return
    namespaces = external.get("namespace")
    if isinstance(namespaces, dict):
        for ns_name, by_gv in sorted(namespaces.items()):
            if not isinstance(by_gv, dict):
                continue
            for gv, by_kind in sorted(by_gv.items()):
                if not isinstance(by_kind, dict):
                    continue
                for kind, by_name in sorted(by_kind.items()):
                    if not isinstance(by_name, dict):
                        continue
                    for name, obj in sorted(by_name.items()):
                        r = make_review(obj, gv, kind, name, namespace=ns_name)
                        if r is not None:
                            yield r
    cluster = external.get("cluster")
    if isinstance(cluster, dict):
        for gv, by_kind in sorted(cluster.items()):
            if not isinstance(by_kind, dict):
                continue
            for kind, by_name in sorted(by_kind.items()):
                if not isinstance(by_name, dict):
                    continue
                for name, obj in sorted(by_name.items()):
                    r = make_review(obj, gv, kind, name)
                    if r is not None:
                        yield r
