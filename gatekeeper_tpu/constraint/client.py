"""The constraint-framework Client: engine-agnostic policy orchestration.

Equivalent of vendor/.../frameworks/constraint/pkg/client/client.go:70-838.
Holds the template/constraint registries, owns the template compile
pipeline, and fans Review/Audit/AddData calls out to target handlers and
the Driver. This is the plugin boundary the controllers, webhook, and
audit manager program against.
"""

from __future__ import annotations

import copy
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ..rego import ast as A
from . import regocompile
from .driver import Driver
from .errors import (
    InvalidConstraintError,
    InvalidTemplateError,
    MissingConstraintError,
    MissingTemplateError,
    UnrecognizedConstraintError,
)
from .templates import (
    CONSTRAINT_GROUP,
    CRD,
    ConstraintTemplate,
    create_crd,
    validate_constraint_against_crd,
)
from .types import Response, Responses

_TARGET_NAME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9.]*$")


@dataclass
class _TemplateEntry:
    template: ConstraintTemplate
    crd: CRD
    targets: List[str]


class Backend:
    """Driver container; hands out a single Client (client/backend.go:28-60)."""

    def __init__(self, driver: Driver):
        self.driver = driver
        self._has_client = False

    def new_client(
        self,
        *targets,
        allowed_data_fields: Sequence[str] = ("inventory",),
    ) -> "Client":
        if self._has_client:
            raise RuntimeError("Backend has already instantiated a client")
        self._has_client = True
        return Client(self, list(targets), allowed_data_fields)


class Client:
    def __init__(
        self,
        backend: Backend,
        targets: List[Any],
        allowed_data_fields: Sequence[str] = ("inventory",),
    ):
        if not targets:
            raise ValueError("No targets registered")
        self._backend = backend
        self._driver = backend.driver
        self._lock = threading.RLock()
        self.targets: Dict[str, Any] = {}
        for t in targets:
            name = t.get_name()
            if not name or not _TARGET_NAME_RE.match(name):
                raise ValueError(f"Invalid target name: {name!r}")
            self.targets[name] = t
            # the driver resolves match semantics (oracle, tensor
            # compile, feature encoding, audit listing) through the
            # handler — register each so multi-target engines route
            # per-target instead of assuming K8s (docs/targets.md)
            register = getattr(self._driver, "register_target", None)
            if register is not None:
                register(t)
        self.allowed_data_fields = list(allowed_data_fields)
        # template name -> entry; (group, kind) -> {subpath: constraint}
        self._templates: Dict[str, _TemplateEntry] = {}
        self._constraints: Dict[Tuple[str, str], Dict[str, dict]] = {}
        # externaldata.ExternalDataSystem (set_external_data): the batch
        # plane external_data lookups resolve through
        self.external_data = None
        self._driver.init()

    def set_external_data(self, system) -> None:
        """Wire the external-data system through the whole evaluation
        stack: the client (batch epochs), the driver (prefetch + the
        extdata row-feature screen), and the interpreter builtin's
        process binding."""
        from ..externaldata import set_system

        self.external_data = system
        hook = getattr(self._driver, "set_external_data", None)
        if hook is not None:
            hook(system)
        set_system(system)

    def _extdata_begin(self) -> None:
        if self.external_data is not None:
            self.external_data.begin_batch()

    # -- template pipeline (client.go:240-470) ------------------------------

    def _create_artifacts(
        self, templ: Union[dict, ConstraintTemplate]
    ) -> Tuple[ConstraintTemplate, CRD, str, List[A.Module], str]:
        ct = (
            templ
            if isinstance(templ, ConstraintTemplate)
            else ConstraintTemplate.from_dict(templ)
        )
        ct.validate_names()
        if len(ct.targets) != 1:
            raise InvalidTemplateError(
                f"expected exactly 1 item in targets, got {len(ct.targets)}"
            )
        spec = ct.targets[0]
        handler = self.targets.get(spec.target)
        if handler is None:
            raise InvalidTemplateError(
                f"target {spec.target!r} not recognized (known: "
                f"{sorted(self.targets)})"
            )
        crd = create_crd(ct, handler.match_schema())
        modules = regocompile.compile_template_modules(
            ct.kind, spec.target, spec.rego, spec.libs, self.allowed_data_fields
        )
        # static vectorizability analysis at admission time: INVALID
        # templates (unsafe vars, broken entrypoints) are rejected HERE
        # with their diagnostics instead of surfacing as an evaluation
        # surprise later; every accepted template carries its report
        from ..analysis import analyze_modules

        report = analyze_modules(ct.kind, modules)
        if report.verdict == "INVALID":
            raise InvalidTemplateError(
                "template failed static analysis:\n" + report.render()
            )
        ct.vectorizability = report
        prefix = f'templates["{spec.target}"]["{ct.kind}"]'
        return ct, crd, spec.target, modules, prefix

    def create_crd(self, templ: Union[dict, ConstraintTemplate]) -> CRD:
        """Validates the full template (including Rego) and returns its CRD
        (client.go:351-359)."""
        _, crd, _, _, _ = self._create_artifacts(templ)
        return crd

    def add_template(self, templ: Union[dict, ConstraintTemplate]) -> Responses:
        resp = Responses()
        ct, crd, target, modules, prefix = self._create_artifacts(templ)
        with self._lock:
            cached = self._templates.get(ct.name)
            if cached is not None and _template_equal(cached.template, ct):
                resp.handled[target] = True
                return resp
            if cached is not None and cached.crd.kind != crd.kind:
                # case-variant kind rename (name==lowercase(kind) still
                # holds): the retired kind's modules and constraints must
                # not stay evaluatable
                self._unmount_kind(cached.targets, cached.crd.kind)
                self._constraints.pop((CONSTRAINT_GROUP, cached.crd.kind), None)
                cached = None
            if cached is not None and cached.targets != [target]:
                # re-targeted template update: unmount the old target's
                # modules and constraint data (or they stay evaluatable),
                # then re-home the cached constraints under the new target
                self._unmount_kind(cached.targets, cached.crd.kind)
                for subpath, c in self._constraints.get(
                    (CONSTRAINT_GROUP, cached.crd.kind), {}
                ).items():
                    self._driver.put_data(f"/constraints/{target}/{subpath}", c)
            self._driver.put_modules(prefix, modules)
            # re-attach the admission-time analyzer report: put_modules
            # just dropped the driver's cached analysis for this kind
            # (warm-swap invalidation), and without this hand-back the
            # /readyz verdict and fallback routing provenance stayed
            # blank until the next dispatch lazily re-analyzed
            attach = getattr(self._driver, "attach_report", None)
            if attach is not None:
                attach(target, ct.kind, ct.vectorizability)
            self._templates[ct.name] = _TemplateEntry(
                template=ct, crd=crd, targets=[target]
            )
            gk = (CONSTRAINT_GROUP, ct.kind)
            self._constraints.setdefault(gk, {})
            resp.handled[target] = True
        return resp

    def _unmount_kind(self, targets, kind: str) -> None:
        """Delete a constraint kind's template modules and constraint-data
        subtree from the driver for every given target. Caller holds
        self._lock."""
        for target in targets:
            self._driver.delete_modules(f'templates["{target}"]["{kind}"]')
            self._driver.delete_data(
                f"/constraints/{target}/cluster/{CONSTRAINT_GROUP}/{kind}"
            )

    def remove_template(self, templ: Union[dict, ConstraintTemplate]) -> Responses:
        resp = Responses()
        ct = (
            templ
            if isinstance(templ, ConstraintTemplate)
            else ConstraintTemplate.from_dict(templ)
        )
        with self._lock:
            entry = self._templates.get(ct.name)
            if entry is None:
                return resp
            target = entry.targets[0]
            self._unmount_kind(entry.targets, entry.crd.kind)
            # the subtree delete covers every constraint of this kind
            self._constraints.pop((CONSTRAINT_GROUP, entry.crd.kind), None)
            del self._templates[ct.name]
            resp.handled[target] = True
        return resp

    def get_template(self, name_or_templ) -> ConstraintTemplate:
        name = (
            name_or_templ
            if isinstance(name_or_templ, str)
            else (
                name_or_templ.name
                if isinstance(name_or_templ, ConstraintTemplate)
                else ConstraintTemplate.from_dict(name_or_templ).name
            )
        )
        with self._lock:
            entry = self._templates.get(name)
            if entry is None:
                raise MissingTemplateError(name)
            return entry.template

    # -- constraints (client.go:473-670) ------------------------------------

    def _get_template_entry(self, constraint: dict) -> _TemplateEntry:
        kind = constraint.get("kind")
        if not kind:
            raise UnrecognizedConstraintError(
                f"Constraint {_cstr_name(constraint)} has no kind"
            )
        group = constraint.get("apiVersion", "").partition("/")[0]
        if group != CONSTRAINT_GROUP:
            raise UnrecognizedConstraintError(
                f"Constraint {_cstr_name(constraint)} has the wrong group: "
                f"{group!r}"
            )
        entry = self._templates.get(kind.lower())
        if entry is None or entry.crd.kind != kind:
            raise UnrecognizedConstraintError(kind)
        return entry

    def add_constraint(self, constraint: dict) -> Responses:
        resp = Responses()
        with self._lock:
            entry = self._get_template_entry(constraint)
            subpath = _constraint_subpath(constraint)
            gk = (CONSTRAINT_GROUP, constraint["kind"])
            cached = self._constraints.get(gk, {}).get(subpath)
            if cached is not None and _constraint_equal(cached, constraint):
                for t in entry.targets:
                    resp.handled[t] = True
                return resp
            self._validate_constraint_locked(constraint, entry)
            for t in entry.targets:
                self._driver.put_data(
                    f"/constraints/{t}/{subpath}", constraint
                )
                resp.handled[t] = True
            self._constraints.setdefault(gk, {})[subpath] = copy.deepcopy(
                constraint
            )
        return resp

    def remove_constraint(self, constraint: dict) -> Responses:
        with self._lock:
            return self._remove_constraint_locked(constraint)

    def _remove_constraint_locked(self, constraint: dict) -> Responses:
        resp = Responses()
        entry = self._get_template_entry(constraint)
        subpath = _constraint_subpath(constraint)
        for t in entry.targets:
            self._driver.delete_data(f"/constraints/{t}/{subpath}")
            resp.handled[t] = True
        gk = (CONSTRAINT_GROUP, constraint["kind"])
        self._constraints.get(gk, {}).pop(subpath, None)
        return resp

    def get_constraint(self, constraint: dict) -> dict:
        with self._lock:
            subpath = _constraint_subpath(constraint)
            gk = (CONSTRAINT_GROUP, constraint.get("kind", ""))
            cached = self._constraints.get(gk, {}).get(subpath)
            if cached is None:
                raise MissingConstraintError(subpath)
            return copy.deepcopy(cached)

    def _validate_constraint_locked(
        self, constraint: dict, entry: _TemplateEntry
    ) -> None:
        validate_constraint_against_crd(constraint, entry.crd)
        for t in entry.targets:
            self.targets[t].validate_constraint(constraint)

    def validate_constraint(self, constraint: dict) -> None:
        with self._lock:
            entry = self._get_template_entry(constraint)
            self._validate_constraint_locked(constraint, entry)

    # -- data (client.go:91-140) --------------------------------------------

    def add_data(self, data: Any) -> Responses:
        resp = Responses()
        for name, handler in self.targets.items():
            handled, path, processed = handler.process_data(data)
            if not handled:
                continue
            self._driver.put_data(f"/external/{name}/{path}", processed)
            resp.handled[name] = True
        return resp

    def remove_data(self, data: Any) -> Responses:
        resp = Responses()
        for name, handler in self.targets.items():
            handled, path, _ = handler.process_data(data)
            if not handled:
                continue
            self._driver.delete_data(f"/external/{name}/{path}")
            resp.handled[name] = True
        return resp

    # -- review / audit (client.go:764-836) ---------------------------------

    def review(self, obj: Any, tracing: bool = False) -> Responses:
        self._extdata_begin()
        responses = Responses()
        for name, handler in self.targets.items():
            handled, review = handler.handle_review(obj)
            if not handled:
                continue
            resp = self._driver.query(
                f'hooks["{name}"].violation', {"review": review}, tracing
            )
            for r in resp.results:
                handler.handle_violation(r)
            resp.target = name
            responses.by_target[name] = resp
        return responses

    def review_many(
        self, objs: Sequence[Any], tracing: bool = False
    ) -> List[Responses]:
        """Batched review: one driver dispatch for the whole batch (the
        micro-batching webhook's entry point; the reference client has no
        equivalent — its webhook evaluates one request per goroutine,
        pkg/webhook/policy.go:141)."""
        self._extdata_begin()
        out: List[Responses] = [Responses() for _ in objs]
        for name, handler in self.targets.items():
            idxs: List[int] = []
            inputs: List[Any] = []
            for i, obj in enumerate(objs):
                handled, review = handler.handle_review(obj)
                if not handled:
                    continue
                idxs.append(i)
                inputs.append({"review": review})
            if not inputs:
                continue
            if self.external_data is not None:
                # batch plane: one deduped prefetch per target BEFORE
                # dispatch, whichever engine (and whichever rung) will
                # evaluate — repeat keys across the batch then answer
                # from the response cache
                self._prefetch_external_for(
                    [i["review"] for i in inputs]
                )
            resps = self._driver.query_many(
                f'hooks["{name}"].violation', inputs, tracing
            )
            for i, resp in zip(idxs, resps):
                for r in resp.results:
                    handler.handle_violation(r)
                resp.target = name
                out[i].by_target[name] = resp
        return out

    def review_many_subset(
        self, objs: Sequence[Any], subset, device: int = 0,
        partition=None,
    ) -> List[Responses]:
        """Partition-scoped batched review (docs/robustness.md §Fault
        domains): one driver dispatch evaluating ONLY `subset`'s
        constraints (keys per `driver.constraint_key`), attributed to
        logical `device`. The partitioned MicroBatcher fans a batch out
        over a PartitionPlan's subsets and merges the per-partition
        results back into the monolithic order. `partition` labels the
        cost-attribution rows (defaults to the device id)."""
        out: List[Responses] = [Responses() for _ in objs]
        for name, handler in self.targets.items():
            idxs: List[int] = []
            inputs: List[Any] = []
            for i, obj in enumerate(objs):
                handled, review = handler.handle_review(obj)
                if not handled:
                    continue
                idxs.append(i)
                inputs.append({"review": review})
            if not inputs:
                continue
            resps = self._driver.query_many_subset(
                f'hooks["{name}"].violation', inputs, subset,
                device=device, partition=partition,
            )
            for i, resp in zip(idxs, resps):
                for r in resp.results:
                    handler.handle_violation(r)
                resp.target = name
                out[i].by_target[name] = resp
        return out

    def partition_match_mask(
        self, objs: Sequence[Any], subsets: Sequence[Any]
    ) -> List[List[bool]]:
        """Per-(partition, request) match screen: True iff the request
        could produce any result from that subset's constraints. The
        partitioned batcher skips partitions nothing in the batch
        touches and scopes the degraded host rung to affected requests
        only (the blast-radius contract)."""
        masks = [[False] * len(objs) for _ in subsets]
        for name, handler in self.targets.items():
            idxs: List[int] = []
            inputs: List[Any] = []
            for i, obj in enumerate(objs):
                handled, review = handler.handle_review(obj)
                if not handled:
                    continue
                idxs.append(i)
                inputs.append({"review": review})
            if not inputs:
                continue
            target_masks = self._driver.partition_match_mask(
                f'hooks["{name}"].violation', inputs, subsets
            )
            for p, tmask in enumerate(target_masks):
                for j, i in enumerate(idxs):
                    masks[p][i] = masks[p][i] or tmask[j]
        return masks

    def prepare_subset(self, subset, device: int = 0) -> bool:
        """Stage one partition's sub-program for every target (the
        quarantine re-home restage step; FaultError from the
        device-labeled restage point propagates so the dispatcher can
        back off)."""
        prep = getattr(self._driver, "prepare_subset", None)
        if prep is None:
            return True
        ok = True
        for name in self.targets:
            # False = lost a race with newer churn (not a failure); the
            # dispatcher leaves the token unstaged and retries
            if prep(f'hooks["{name}"].violation', subset, device=device) is False:
                ok = False
        return ok

    def prefetch_external(self, objs: Sequence[Any]) -> None:
        """Batch-plane external-data prefetch for a review batch that
        will evaluate per-request (the host-interpreter rung): opens a
        fetch epoch and dedupes/fetches the batch's keys once per
        provider, so the per-request evaluations that follow serve from
        the response cache. Best-effort; no-op without a wired
        system."""
        if self.external_data is None:
            return
        self.external_data.begin_batch()
        for name, handler in self.targets.items():
            reviews = []
            for obj in objs:
                handled, review = handler.handle_review(obj)
                if handled:
                    reviews.append(review)
            if reviews:
                self._prefetch_external_for(reviews)

    def _prefetch_external_for(self, reviews: Sequence[Any]) -> None:
        """Engine-agnostic batch prefetch: extract + dedupe the batch's
        external-data keys from the ingested templates' recorded call
        sites, then at most one outbound fetch per provider. Works for
        any driver exposing the interpreter (the TPU driver ALSO
        prefetches on its own dispatch path — idempotent, the second
        pass finds no misses)."""
        system = self.external_data
        interp = getattr(self._driver, "interp", None)
        if system is None or interp is None:
            return
        try:
            from ..externaldata.extract import batch_wants

            with self._lock:
                entries = list(self._templates.values())
            wants_total: Dict[str, set] = {}
            # extraction evaluates against the driver-mounted modules:
            # hold the driver's mutation mutex (reads race module churn
            # otherwise), but NEVER during the outbound fetch below
            mutex = self._driver._mutex if hasattr(
                self._driver, "_mutex"
            ) else threading.RLock()
            with mutex:
                for e in entries:
                    rep = getattr(e.template, "vectorizability", None)
                    calls = getattr(rep, "external_calls", None)
                    if not calls:
                        continue
                    w = batch_wants(interp, calls, reviews)
                    if w:
                        for p, ks in w.items():
                            wants_total.setdefault(p, set()).update(ks)
            if wants_total:
                system.prefetch(wants_total)
        except Exception:
            pass

    def review_host(self, obj: Any, subset=None) -> Responses:
        """Host-interpreter review: the degraded rung of the admission
        ladder (docs/robustness.md). Same results as `review` by the
        driver-parity contract, but pinned to the host so a faulted
        device path is never re-attempted per request — the micro-batch
        worker calls this when the fused dispatch fails or the circuit
        breaker is open. `subset` scopes the evaluation to one
        partition's constraints (§Fault domains): a sick device
        degrades only its own constraint subset to the interpreter."""
        responses = Responses()
        for name, handler in self.targets.items():
            handled, review = handler.handle_review(obj)
            if not handled:
                continue
            resp = self._driver.query_host(
                f'hooks["{name}"].violation', {"review": review},
                subset=subset,
            )
            for r in resp.results:
                handler.handle_violation(r)
            resp.target = name
            responses.by_target[name] = resp
        return responses

    def warm_review_path(self, objs: Sequence[Any]) -> bool:
        """Synchronously compile the driver's fused review path for
        `objs`' batch shapes (serve-while-compiling, VERDICT r4 #4) —
        the review_many conversion without the evaluation. Drivers with
        no compile step (the interpreter) are trivially warm."""
        warm = getattr(self._driver, "warm_review_path", None)
        if warm is None:
            return True
        ok = True
        for name, handler in self.targets.items():
            reviews = []
            for obj in objs:
                handled, review = handler.handle_review(obj)
                if handled:
                    reviews.append(review)
            if reviews:
                ok = warm(name, reviews) and ok
        return ok

    def audit(self, tracing: bool = False) -> Responses:
        self._extdata_begin()
        responses = Responses()
        for name, handler in self.targets.items():
            resp = self._driver.query(f'hooks["{name}"].audit', None, tracing)
            for r in resp.results:
                handler.handle_violation(r)
            resp.target = name
            responses.by_target[name] = resp
        return responses

    # -- maintenance (client.go:725-748, 837) -------------------------------

    def reset(self) -> None:
        with self._lock:
            for name in self.targets:
                self._driver.delete_data(f"/external/{name}")
                self._driver.delete_data(f"/constraints/{name}")
            for name, entry in self._templates.items():
                for t in entry.targets:
                    self._driver.delete_modules(
                        f'templates["{t}"]["{entry.crd.kind}"]'
                    )
            self._templates = {}
            self._constraints = {}

    def dump(self) -> str:
        return self._driver.dump()

    # -- introspection -------------------------------------------------------

    def known_templates(self) -> List[str]:
        with self._lock:
            return sorted(self._templates)

    def template_report(self, name_or_kind: str):
        """Vectorizability report for an ingested template (by template
        name or constraint kind); None when unknown."""
        with self._lock:
            entry = self._templates.get(name_or_kind) or self._templates.get(
                name_or_kind.lower()
            )
            if entry is None:
                return None
            return entry.template.vectorizability

    def template_reports(self) -> Dict[str, Any]:
        """{template name -> VectorizabilityReport} for every ingested
        template (webhook/status introspection surface)."""
        with self._lock:
            return {
                name: e.template.vectorizability
                for name, e in self._templates.items()
            }

    def known_constraint_kinds(self) -> List[str]:
        with self._lock:
            return sorted(e.crd.kind for e in self._templates.values())


def _cstr_name(constraint: dict) -> str:
    return ((constraint.get("metadata") or {}).get("name")) or "?"


def _constraint_subpath(constraint: dict) -> str:
    """createConstraintSubPath (client.go:473-486):
    cluster/<group>/<kind>/<name>."""
    name = _cstr_name(constraint)
    if name == "?":
        raise InvalidConstraintError("Constraint has no name")
    group = constraint.get("apiVersion", "").partition("/")[0]
    kind = constraint.get("kind")
    if not group:
        raise InvalidConstraintError(
            f"Empty group for the constraint named {name}"
        )
    if not kind:
        raise InvalidConstraintError(
            f"Empty kind for the constraint named {name}"
        )
    return f"cluster/{group}/{kind}/{name}"


def _strip_status(obj: dict) -> dict:
    out = copy.deepcopy(obj)
    out.pop("status", None)
    return out


def _template_equal(a: ConstraintTemplate, b: ConstraintTemplate) -> bool:
    """SemanticEqual (templates): spec comparison, status ignored.

    Raw specs are compared when both templates carry them; directly
    constructed ConstraintTemplate objects (empty raw) fall back to their
    substantive fields so updates are never silently dropped.
    """
    spec_a = _strip_status(a.raw).get("spec")
    spec_b = _strip_status(b.raw).get("spec")
    if spec_a is not None and spec_b is not None:
        return spec_a == spec_b
    return (
        a.kind == b.kind
        and a.parameters_schema == b.parameters_schema
        and [(t.target, t.rego, tuple(t.libs)) for t in a.targets]
        == [(t.target, t.rego, tuple(t.libs)) for t in b.targets]
    )


def _constraint_equal(a: dict, b: dict) -> bool:
    """constraints.SemanticEqual: spec + enforcement comparison, status
    ignored."""
    sa, sb = _strip_status(a), _strip_status(b)
    return sa.get("spec") == sb.get("spec") and sa.get("metadata", {}).get(
        "deletionTimestamp"
    ) == sb.get("metadata", {}).get("deletionTimestamp")
