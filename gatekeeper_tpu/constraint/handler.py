"""TargetHandler: the target plugin boundary (docs/targets.md).

The reference's client.TargetHandler interface (frameworks/constraint/
pkg/client/client.go + pkg/handler) covers data ingestion, review
normalization, violation post-processing, and the match schema. This
build's fused evaluation engine needs more from a target than the
reference's interpreter did — the match ORACLE, the match TENSOR
compiler, review feature encoding, audit listing, review construction
for the webhook plane, and exemption hooks — so all of those live here
too, as overridable methods with defaults that delegate to the shared
match-semantics engine (`constraint/match.py`, `engine/matchspec.py`,
`flatten/encoder.py`).

Those engine modules speak one internal review/match vocabulary — the
gkReview dict shape and the kinds/namespaces/labelSelector/
namespaceSelector match-block shape. A target whose public schema IS
that vocabulary (K8s) inherits the defaults unchanged; any other target
(agentaction/) translates its schema into the vocabulary via
`match_ir()` + `handle_review()` and gets the whole fused stack —
kernel match, analyzer, symbolic compiler, mutation screens, external
data — for free. Nothing outside this boundary imports the
match-semantics modules directly (enforced by the genericity gate in
tests/test_agentaction.py).
"""

from __future__ import annotations

import re
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .errors import InvalidConstraintError
from .types import Result


class WipeData:
    """Sentinel: deletes the target's whole data subtree (target.go:37-41)."""


class TargetHandler(ABC):
    """Target plugin: schema translation + data/review normalization."""

    # -- the reference's six-method surface ---------------------------------

    @abstractmethod
    def get_name(self) -> str: ...

    @abstractmethod
    def process_data(self, obj: Any) -> Tuple[bool, str, Any]: ...

    @abstractmethod
    def handle_review(self, obj: Any) -> Tuple[bool, Any]: ...

    @abstractmethod
    def handle_violation(self, result: Result) -> None: ...

    @abstractmethod
    def match_schema(self) -> Dict[str, Any]: ...

    @abstractmethod
    def validate_constraint(self, constraint: Dict[str, Any]) -> None: ...

    # -- match semantics (engine-facing) ------------------------------------

    def match_ir(self, constraint: Dict[str, Any]) -> Any:
        """The constraint's match block translated into the engine's
        internal match vocabulary. Identity for targets whose public
        schema is the engine vocabulary."""
        from .hooks import constraint_match

        return constraint_match(constraint)

    def matches_constraint(
        self, constraint: Dict[str, Any], review: Any, ctx_cache: Dict
    ) -> bool:
        """The host match oracle for one (constraint, review) pair."""
        from . import match as M

        return M.matches_match(self.match_ir(constraint), review, ctx_cache)

    def constraint_needs_context(self, constraint: Dict[str, Any]) -> bool:
        """The constraint-side half of the autoreject factoring (see
        match.needs_ns_selector): True when evaluating this constraint
        requires a resolved review context object."""
        from . import match as M

        return M.match_needs_ns_selector(self.match_ir(constraint))

    def review_autorejects(self, review: Any, ctx_cache: Dict) -> bool:
        """The review-side half: the review names a context object that
        is neither attached nor cached."""
        from . import match as M

        return M.review_autorejects(review, ctx_cache)

    def compile_match_specs(
        self, constraints: List[Dict[str, Any]], vocab: Any
    ):
        """Constraint-side match tensors for the fused kernel."""
        from ..engine.matchspec import compile_match_irs

        return compile_match_irs(
            [self.match_ir(c) for c in constraints], vocab
        )

    def encode_review_features(self, review: Any, ctx_cache: Dict, vocab: Any):
        """Review-side match features for the fused kernel."""
        from ..flatten.encoder import encode_review_features

        return encode_review_features(review, ctx_cache, vocab)

    def review_context_cache(
        self, storage_get: Callable[[List[str], Any], Any]
    ) -> Dict[str, Any]:
        """The synced context objects reviews resolve selectors against
        (the K8s Namespace cache). `storage_get(path, default)` reads
        the driver's data tree. Default: no context cache."""
        return {}

    # -- audit listing -------------------------------------------------------

    def iter_cached_reviews(self, external: Any) -> Iterator[Any]:
        """Reviews for every object in this target's synced data
        subtree (the audit cross-join's review stream)."""
        from . import match as M

        return M.iter_cached_reviews(external)

    def wrap_audit_object(self, obj: Any, context: Any = None) -> Any:
        """A listed object + its (optional) context object, in the
        shape handle_review() accepts — the audit manager's review
        construction."""
        return obj

    # -- webhook plane -------------------------------------------------------

    def augment_request(
        self,
        request: Dict[str, Any],
        context_getter: Optional[Callable[[str], Optional[dict]]] = None,
    ) -> Any:
        """An incoming serving-plane request in the shape
        handle_review() accepts, with its context object attached (the
        webhook's review construction). Default: pass through."""
        return request

    def request_exempt(
        self, request: Dict[str, Any], excluder: Any, process: str
    ) -> Optional[str]:
        """Process-exclusion hook: a non-None reason admits the request
        without evaluation (the K8s excluded-namespaces config)."""
        return None

    def sample_requests(self, n: int) -> List[Dict[str, Any]]:
        """Synthetic serving-plane requests for compile warmup (shape
        coverage only; never evaluated against real state)."""
        return []


def handler_for(client: Any, target: str) -> TargetHandler:
    """Resolve `target`'s handler from a Client's registry, tolerating
    registry-less test fakes (K8s default, like the drivers)."""
    registry = getattr(client, "targets", None) or {}
    h = registry.get(target)
    return h if h is not None else default_handler()


def default_handler() -> TargetHandler:
    """The compatibility default for drivers queried about a target
    name no handler was registered for: the K8s target (every pre-
    multi-target call site assumed it)."""
    from .target import K8sValidationTarget

    return K8sValidationTarget()


# -- shared selector validation ---------------------------------------------

_LABEL_VALUE_RE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")


def label_selector_schema() -> Dict[str, Any]:
    """The matchExpressions/matchLabels selector schema fragment shared
    by every target's match_schema()."""
    string_list = {"type": "array", "items": {"type": "string"}}
    return {
        "type": "object",
        "properties": {
            "matchExpressions": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "key": {"type": "string"},
                        "operator": {
                            "type": "string",
                            "enum": ["In", "NotIn", "Exists", "DoesNotExist"],
                        },
                        "values": string_list,
                    },
                },
            }
        },
    }


def validate_label_selector(selector: Dict[str, Any], path: str) -> None:
    """Mirrors metav1 validation.ValidateLabelSelector: operator-specific
    values rules and label-value syntax for In/NotIn values."""
    exprs = selector.get("matchExpressions")
    if not isinstance(exprs, list):
        return
    for i, expr in enumerate(exprs):
        if not isinstance(expr, dict):
            raise InvalidConstraintError(
                f"{path}.matchExpressions[{i}]: must be an object"
            )
        op = expr.get("operator")
        values = expr.get("values") or []
        if op in ("In", "NotIn"):
            if not values:
                raise InvalidConstraintError(
                    f"{path}.matchExpressions[{i}].values: must be specified "
                    f"when `operator` is 'In' or 'NotIn'"
                )
        elif op in ("Exists", "DoesNotExist"):
            if values:
                raise InvalidConstraintError(
                    f"{path}.matchExpressions[{i}].values: may not be "
                    f"specified when `operator` is 'Exists' or 'DoesNotExist'"
                )
        else:
            raise InvalidConstraintError(
                f"{path}.matchExpressions[{i}].operator: not a valid selector "
                f"operator: {op!r}"
            )
        for v in values:
            if not isinstance(v, str) or len(v) > 63 or not _LABEL_VALUE_RE.match(v):
                raise InvalidConstraintError(
                    f"{path}.matchExpressions[{i}].values: invalid label "
                    f"value: {v!r}"
                )
