"""Template Rego compile pipeline: parse, validate, namespace-rewrite.

AST-level equivalent of the reference's regorewriter + rego_helpers
(vendor/.../frameworks/constraint/pkg/regorewriter/regorewriter.go,
client/rego_helpers.go:17-100, client/client.go:280-345): entry-point
violation-arity enforcement, package rewriting into the per-template
namespace, lib package prefixing, `data.lib` reference rewriting, and
data-extern allowlisting. Operating on parsed ASTs (not source text) means
template kinds/targets containing dots can't corrupt paths and the driver
mounts modules without re-parsing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Set

from ..rego import ast as A
from ..rego.parser import ParseError, parse_module
from .errors import InvalidTemplateError


def parse_template_module(src: str) -> A.Module:
    if not src or not src.strip():
        raise InvalidTemplateError("Empty module")
    try:
        return parse_module(src)
    except ParseError as e:
        raise InvalidTemplateError(f"Rego parse error: {e}") from e


def rule_arity(rule: A.Rule) -> int:
    """getRuleArity (client/rego_helpers.go:75-100): partial-set keys count
    as arity 1 (var or object), arrays of vars/objects as their length."""
    t = rule.head.key
    if t is None:
        return 0
    if isinstance(t, (A.Var, A.Wildcard, A.ObjectTerm)):
        return 1
    if isinstance(t, A.ArrayTerm):
        for e in t.items:
            if not isinstance(e, (A.Var, A.Wildcard, A.ObjectTerm)):
                raise InvalidTemplateError(
                    "Invalid rule signature: only single variables or arrays "
                    "of variables or objects allowed"
                )
        return len(t.items)
    raise InvalidTemplateError(
        "Invalid rule signature, only variables or arrays allowed"
    )


def require_rules(module: A.Module, required: dict) -> None:
    """requireRulesModule (client/rego_helpers.go:45-72)."""
    arities = {}
    for rule in module.rules:
        arities[rule.head.name] = rule_arity(rule)
    errs = []
    for name, arity in required.items():
        if name not in arities:
            errs.append(f"Missing required rule: {name}")
        elif arities[name] != arity:
            errs.append(f"Rule {name} has arity {arities[name]}, want {arity}")
    if errs:
        raise InvalidTemplateError("Invalid rego: " + "; ".join(errs))


# -- generic AST walk -------------------------------------------------------


def _walk(node: Any, visit: Callable[[Any], None]) -> None:
    """Depth-first walk over every AST node reachable from `node`."""
    if isinstance(node, A.Node):
        visit(node)
        for f in dataclasses.fields(node):  # type: ignore[arg-type]
            _walk(getattr(node, f.name), visit)
    elif isinstance(node, (list, tuple)):
        for item in node:
            _walk(item, visit)


def walk_module(module: A.Module, visit: Callable[[Any], None]) -> None:
    _walk(module.rules, visit)


def _import_data_head(imp: A.Import) -> Optional[str]:
    if len(imp.path) >= 2 and imp.path[0] == "data":
        return imp.path[1]
    return None


# -- namespace rewriting ----------------------------------------------------


def _data_ref_head(term: A.Ref) -> Optional[str]:
    """For a ref rooted at `data`, return the first path segment (or None)."""
    if isinstance(term.head, A.Var) and term.head.name == "data" and term.ops:
        first = term.ops[0]
        if isinstance(first, A.Scalar) and isinstance(first.value, str):
            return first.value
    return None


def validate_externs(module: A.Module, allowed: Sequence[str]) -> None:
    """Reject data.<field> references outside the allowlist
    (client/client.go:286-298 wires {data.lib} + allowedDataFields)."""
    allowed_set: Set[str] = set(allowed)
    bad: List[str] = []

    def visit(node: Any) -> None:
        if isinstance(node, A.Ref):
            head = _data_ref_head(node)
            if head is not None and head not in allowed_set:
                bad.append(f"data.{head}")
        elif isinstance(node, A.Call) and node.name.startswith("data."):
            seg = node.name.split(".")[1]
            if seg not in allowed_set:
                bad.append(f"data.{seg}")

    walk_module(module, visit)
    for imp in module.imports:
        head = _import_data_head(imp)
        if head is not None and head not in allowed_set:
            bad.append(f"data.{head}")
    if bad:
        raise InvalidTemplateError(
            f"invalid data references: {sorted(set(bad))} (allowed: "
            f"{sorted(allowed_set)})"
        )


def rewrite_lib_refs(module: A.Module, ns: str) -> None:
    """Rewrite data.lib.X -> data.libs.<ns>.lib.X (refs and call names).

    regorewriter's PackagePrefixer equivalent; `ns` is the template kind,
    which is unique per template and dot-free (so call-name paths stay
    unambiguous even for targets with dots in their name).
    """

    def visit(node: Any) -> None:
        if isinstance(node, A.Ref):
            if _data_ref_head(node) == "lib":
                node.ops[0:0] = [A.Scalar("libs"), A.Scalar(ns)]
        elif isinstance(node, A.Call):
            if node.name.startswith("data.lib."):
                node.name = f"data.libs.{ns}.lib." + node.name[len("data.lib.") :]

    walk_module(module, visit)
    for imp in module.imports:
        if _import_data_head(imp) == "lib":
            imp.path[1:1] = ["libs", ns]


def compile_template_modules(
    kind: str,
    target_name: str,
    rego_src: str,
    lib_srcs: Sequence[str],
    allowed_data_fields: Sequence[str] = ("inventory",),
) -> List[A.Module]:
    """Full pipeline: returns mounted-ready modules (entry first).

    The entry module's package becomes ["templates", <target>, <Kind>]
    (createTemplatePath, client/client.go:142-145); each lib's package gets
    the ["libs", <Kind>] prefix (templateLibPrefix, :147-150 — target elided
    for path-safety, kind is already unique).
    """
    entry = parse_template_module(rego_src)
    require_rules(entry, {"violation": 1})
    validate_externs(entry, ["lib", *allowed_data_fields])
    rewrite_lib_refs(entry, kind)
    entry.package = ["templates", target_name, kind]

    modules = [entry]
    for lib_src in lib_srcs:
        lib = parse_template_module(lib_src)
        if not lib.package or lib.package[0] != "lib":
            raise InvalidTemplateError(
                f"the lib package must begin with `lib`, got "
                f"{'.'.join(lib.package)!r}"
            )
        validate_externs(lib, ["lib", *allowed_data_fields])
        rewrite_lib_refs(lib, kind)
        lib.package = ["libs", kind, *lib.package]
        modules.append(lib)
    return modules
