"""Wire types between drivers and callers.

Shape-compatible with the reference's
vendor/.../frameworks/constraint/pkg/types/validation.go:11-63.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Result:
    # messages reported by the violation rule
    msg: str = ""
    # arbitrary supplemental details from the violation rule
    metadata: Dict[str, Any] = field(default_factory=dict)
    # the constraint (full unstructured object) that was violated
    constraint: Optional[Dict[str, Any]] = None
    # the review object evaluated
    review: Any = None
    # the violating resource, extracted from the review by the target handler
    resource: Any = None
    # "deny" | "dryrun" (unrecognized values pass through)
    enforcement_action: str = "deny"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "msg": self.msg,
            "metadata": self.metadata,
            "constraint": self.constraint,
            "review": self.review,
            "resource": self.resource,
            "enforcementAction": self.enforcement_action,
        }


@dataclass
class Response:
    trace: Optional[str] = None
    input: Optional[str] = None
    target: str = ""
    results: List[Result] = field(default_factory=list)

    def sorted_results(self) -> List[Result]:
        return sorted(self.results, key=lambda r: r.msg)


@dataclass
class Responses:
    by_target: Dict[str, Response] = field(default_factory=dict)
    handled: Dict[str, bool] = field(default_factory=dict)

    def results(self) -> List[Result]:
        out: List[Result] = []
        for target in sorted(self.by_target):
            out.extend(self.by_target[target].results)
        return out

    def traces(self) -> str:
        lines = []
        for target in sorted(self.by_target):
            resp = self.by_target[target]
            if resp.trace is None:
                continue
            lines.append(resp.trace)
            lines.append(f"target: {target}")
        return "\n\n".join(lines)
