"""K8sValidationTarget: the Kubernetes-specific data model plugin.

TPU-native equivalent of /root/reference/pkg/target/target.go:23-354. The
target handler owns: routing synced cluster objects into the driver's data
tree, normalizing the three review input shapes into a gkReview, extracting
the violating resource from results, and the constraint `spec.match` schema.

The Rego matching library the reference pairs with this handler
(target_template_source.go) lives natively in match.py instead.
"""

from __future__ import annotations

import json
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .handler import (
    TargetHandler,
    WipeData,  # noqa: F401  (historic home; re-exported for importers)
    label_selector_schema,
    validate_label_selector,
)
from .types import Result


@dataclass
class AdmissionRequest:
    """A typed wrapper marking a dict as an AdmissionRequest review."""

    request: Dict[str, Any]


@dataclass
class AugmentedReview:
    """AdmissionRequest + its (optional) Namespace object (target.go:43-46)."""

    admission_request: Dict[str, Any]
    namespace: Optional[Dict[str, Any]] = None


@dataclass
class AugmentedUnstructured:
    """A cluster object + its Namespace, used by audit (target.go:53-56)."""

    object: Dict[str, Any]
    namespace: Optional[Dict[str, Any]] = None


def _gvk_of(obj: Dict[str, Any]) -> Tuple[str, str, str]:
    api_version = obj.get("apiVersion", "") or ""
    kind = obj.get("kind", "") or ""
    if "/" in api_version:
        group, version = api_version.split("/", 1)
    else:
        group, version = "", api_version
    return group, version, kind


def _meta(obj: Dict[str, Any], key: str) -> str:
    metadata = obj.get("metadata")
    if isinstance(metadata, dict):
        val = metadata.get(key)
        if isinstance(val, str):
            return val
    return ""


def _unstructured_to_admission_request(obj: Dict[str, Any]) -> Dict[str, Any]:
    """unstructuredToAdmissionRequest (target.go:144-163): kind + object +
    name only — namespace deliberately NOT set."""
    group, version, kind = _gvk_of(obj)
    return {
        "kind": {"group": group, "version": version, "kind": kind},
        "object": obj,
        "name": _meta(obj, "name"),
    }


class K8sValidationTarget(TargetHandler):
    """TargetHandler implementation for Kubernetes admission data.

    The engine's internal match/review vocabulary IS this target's
    public schema, so every engine-facing TargetHandler default
    (match_ir / matches_constraint / compile_match_specs / feature
    encoding / audit listing) applies unchanged; only the K8s-specific
    pieces — the Namespace context cache, AdmissionReview construction,
    namespace exclusion, and warmup shapes — are overridden below."""

    def get_name(self) -> str:
        return "admission.k8s.gatekeeper.sh"

    # -- data ingestion (target.go:62-89) ----------------------------------

    def process_data(self, obj: Any) -> Tuple[bool, str, Any]:
        """Returns (handled, relative path, processed data).

        Paths: cluster/<escaped groupVersion>/<kind>/<name> or
        namespace/<ns>/<escaped groupVersion>/<kind>/<name>; the
        groupVersion is url-path-escaped exactly as the reference does
        (target.go:73-75), so "apps/v1" becomes "apps%2Fv1".
        """
        if isinstance(obj, WipeData) or obj is WipeData:
            return True, "", None
        if not isinstance(obj, dict):
            return False, "", None
        group, version, kind = _gvk_of(obj)
        name = _meta(obj, "name")
        if version == "":
            raise ValueError(f"resource {name} has no version")
        if kind == "":
            raise ValueError(f"resource {name} has no kind")
        gv = f"{group}/{version}" if group else version
        # Go url.PathEscape (encodePathSegment): '$&+:=@' and unreserved
        # stay raw; '/;,?' and the RFC sub-delims !*'() are escaped
        gv = urllib.parse.quote(gv, safe="$&+:=@")
        namespace = _meta(obj, "namespace")
        if namespace == "":
            return True, f"cluster/{gv}/{kind}/{name}", obj
        return True, f"namespace/{namespace}/{gv}/{kind}/{name}", obj

    # -- review normalization (target.go:91-142) ---------------------------

    def handle_review(self, obj: Any) -> Tuple[bool, Any]:
        """Normalizes review inputs into the gkReview dict shape."""
        if isinstance(obj, AdmissionRequest):
            return True, obj.request
        if isinstance(obj, AugmentedReview):
            review = dict(obj.admission_request)
            review["_unstable"] = (
                {"namespace": obj.namespace} if obj.namespace is not None else {}
            )
            return True, review
        if isinstance(obj, AugmentedUnstructured):
            review = _unstructured_to_admission_request(obj.object)
            review["_unstable"] = (
                {"namespace": obj.namespace} if obj.namespace is not None else {}
            )
            if obj.namespace is not None:
                review["namespace"] = _meta(obj.namespace, "name")
            return True, review
        if isinstance(obj, dict):
            # raw dicts are treated as unstructured cluster objects, matching
            # the reference's unstructured.Unstructured case (target.go:113)
            return True, _unstructured_to_admission_request(obj)
        return False, None

    # -- violation post-processing (target.go:193-244) ---------------------

    def handle_violation(self, result: Result) -> None:
        review = result.review
        if not isinstance(review, dict):
            raise ValueError(f"could not cast review as map: {review!r}")
        kind_info = review.get("kind")
        if not isinstance(kind_info, dict):
            raise ValueError("review[kind] does not exist")
        fields = {}
        for k in ("group", "version", "kind"):
            v = kind_info.get(k)
            if not isinstance(v, str):
                raise ValueError(f"review[kind][{k}] is not a string: {v!r}")
            fields[k] = v
        api_version = (
            fields["version"]
            if fields["group"] == ""
            else f"{fields['group']}/{fields['version']}"
        )
        obj = review.get("object")
        if not isinstance(obj, dict):
            obj = review.get("oldObject")
        if not isinstance(obj, dict):
            raise ValueError("no object or oldObject returned in review")
        resource = json.loads(json.dumps(obj))
        resource["apiVersion"] = api_version
        resource["kind"] = fields["kind"]
        result.resource = resource

    # -- constraint spec.match schema (target.go:246-318) ------------------

    def match_schema(self) -> Dict[str, Any]:
        string_list = {"type": "array", "items": {"type": "string"}}
        label_selector = label_selector_schema()
        return {
            "type": "object",
            "properties": {
                "kinds": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "apiGroups": string_list,
                            "kinds": string_list,
                        },
                    },
                },
                "namespaces": string_list,
                "excludedNamespaces": string_list,
                "labelSelector": label_selector,
                "namespaceSelector": label_selector,
                "scope": {
                    "type": "string",
                    "enum": ["*", "Cluster", "Namespaced"],
                },
            },
        }

    # -- constraint validation (target.go:320-354) -------------------------

    def validate_constraint(self, constraint: Dict[str, Any]) -> None:
        spec = constraint.get("spec")
        match = spec.get("match") if isinstance(spec, dict) else None
        if not isinstance(match, dict):
            return
        for sel_field in ("labelSelector", "namespaceSelector"):
            selector = match.get(sel_field)
            if isinstance(selector, dict):
                validate_label_selector(selector, sel_field)

    # -- engine-facing overrides (docs/targets.md) --------------------------

    def review_context_cache(self, storage_get) -> Dict[str, Any]:
        """The synced Namespace cache — what namespaceSelector and
        autoreject resolve reviews against (target_template_source.go's
        data.external.<t>.cluster.v1.Namespace lookups)."""
        cache = storage_get(
            ["external", self.get_name(), "cluster", "v1", "Namespace"], {}
        )
        return cache if isinstance(cache, dict) else {}

    def augment_request(
        self,
        request: Dict[str, Any],
        context_getter: Optional[Callable[[str], Optional[dict]]] = None,
    ) -> Any:
        """AdmissionRequest -> AugmentedReview with the Namespace object
        attached (pkg/webhook/policy.go:354-369's nsCache.Get)."""
        ns_obj = None
        namespace = request.get("namespace", "")
        if namespace and context_getter is not None:
            ns_obj = context_getter(namespace)
        return AugmentedReview(request, namespace=ns_obj)

    def wrap_audit_object(self, obj: Any, context: Any = None) -> Any:
        return AugmentedUnstructured(obj, context)

    def request_exempt(
        self, request: Dict[str, Any], excluder: Any, process: str
    ) -> Optional[str]:
        namespace = request.get("namespace", "")
        if (
            namespace
            and excluder is not None
            and excluder.is_namespace_excluded(process, namespace)
        ):
            return "Namespace is set to be ignored by Gatekeeper config"
        return None

    def sample_requests(self, n: int) -> List[Dict[str, Any]]:
        """Warmup AdmissionRequests: label counts vary so both
        feature-shape buckets compile."""
        out = []
        for i in range(n):
            obj = _warm_pod(1 + (i % 2) * 7)
            out.append(
                {
                    "uid": f"warmup-{i}",
                    "kind": {
                        "group": "",
                        "version": "v1",
                        "kind": obj.get("kind", "Pod"),
                    },
                    "operation": "CREATE",
                    "name": f"warmup-{i}",
                    "namespace": "default",
                    "userInfo": {"username": "system:warmup"},
                    "object": obj,
                }
            )
        return out


def _warm_pod(n_labels: int) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": "warmup",
            "namespace": "default",
            "labels": {f"k{i}": f"v{i}" for i in range(n_labels)},
        },
        "spec": {
            "containers": [
                {
                    "name": "main",
                    "image": "warmup.invalid/img",
                    "resources": {"limits": {"cpu": "1", "memory": "1Gi"}},
                    "securityContext": {"privileged": False},
                }
            ]
        },
    }
