"""Constraint framework: the engine-agnostic policy orchestration layer.

This is the TPU-native equivalent of the reference's vendored
open-policy-agent/frameworks constraint client
(/root/reference/vendor/github.com/open-policy-agent/frameworks/constraint/
pkg/client/client.go:70-838). The `Client` is the plugin boundary: controllers,
webhook, and audit only see `Client`; evaluation engines are swappable behind
the `Driver` interface (drivers/interface.go:21-39 in the reference).

Architectural departure from the reference (tpu-first): constraint↔review
matching is NOT an interpreted Rego library installed into the engine
(reference: pkg/target/target_template_source.go). It is implemented natively
in `match.py` — one shared semantics oracle that (a) serves the CPU driver
per-review and (b) compiles to the vectorized [n_constraints, n_resources]
JAX match kernel used by the TPU driver. Only ConstraintTemplate `violation`
rules go through the Rego evaluator (interpreter on CPU, compiled kernels on
TPU).
"""

from .types import Result, Response, Responses  # noqa: F401
from .errors import (  # noqa: F401
    ConstraintFrameworkError,
    MissingTemplateError,
    UnrecognizedConstraintError,
    InvalidTemplateError,
    InvalidConstraintError,
)
from .datastore import DataStore, PathConflictError  # noqa: F401
from .driver import Driver, RegoDriver  # noqa: F401
from .handler import (  # noqa: F401
    TargetHandler,
    WipeData,
    default_handler,
    label_selector_schema,
    validate_label_selector,
)
from .target import (  # noqa: F401
    AdmissionRequest,
    AugmentedReview,
    AugmentedUnstructured,
    K8sValidationTarget,
)
from .templates import ConstraintTemplate, CRD  # noqa: F401
from .client import Client, Backend  # noqa: F401


def __getattr__(name):
    # lazy: tpudriver pulls in the engine package, which itself imports
    # constraint.match — a cycle if resolved during this __init__
    if name == "TpuDriver":
        from .tpudriver import TpuDriver

        return TpuDriver
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
