"""ConstraintTemplate model, CRD construction, and constraint validation.

Covers the reference's template compile pipeline entry
(vendor/.../frameworks/constraint/pkg/client/client.go:240-351 +
crd_helpers.go:40-140): name==lowercase(kind) check, single-target
validation, CRD schema assembly (match schema + enforcementAction +
template-declared parameters schema), and CR validation against that schema.

The apiextensions validation machinery is replaced with a small JSON-Schema
subset validator sufficient for the schemas the library templates declare
(type/properties/items/enum/maxLength — v1beta1 CRD validation is
non-structural and permissive about unknown fields, which this mirrors).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .errors import InvalidConstraintError, InvalidTemplateError

CONSTRAINT_GROUP = "constraints.gatekeeper.sh"
CONSTRAINT_API_VERSION = f"{CONSTRAINT_GROUP}/v1beta1"
TEMPLATE_GROUP = "templates.gatekeeper.sh"
SUPPORTED_TEMPLATE_VERSIONS = ("v1alpha1", "v1beta1")


@dataclass
class TargetSpec:
    target: str
    rego: str
    libs: List[str] = field(default_factory=list)


@dataclass
class ConstraintTemplate:
    """Parsed ConstraintTemplate (apis/templates v1alpha1/v1beta1)."""

    name: str
    kind: str
    targets: List[TargetSpec]
    parameters_schema: Optional[Dict[str, Any]] = None
    api_version: str = f"{TEMPLATE_GROUP}/v1beta1"
    labels: Dict[str, str] = field(default_factory=dict)
    raw: Dict[str, Any] = field(default_factory=dict)
    # static vectorizability analysis (analysis.VectorizabilityReport),
    # attached by the Client's compile pipeline at admission time
    vectorizability: Optional[Any] = None

    @classmethod
    def from_dict(cls, obj: Dict[str, Any]) -> "ConstraintTemplate":
        if not isinstance(obj, dict):
            raise InvalidTemplateError("template must be an object")
        api_version = obj.get("apiVersion", "")
        group, _, version = api_version.partition("/")
        if group != TEMPLATE_GROUP or version not in SUPPORTED_TEMPLATE_VERSIONS:
            raise InvalidTemplateError(
                f"unsupported template apiVersion: {api_version!r}"
            )
        if obj.get("kind") != "ConstraintTemplate":
            raise InvalidTemplateError(f"not a ConstraintTemplate: {obj.get('kind')!r}")
        metadata = obj.get("metadata") or {}
        name = metadata.get("name", "")
        spec = obj.get("spec") or {}
        crd_spec = ((spec.get("crd") or {}).get("spec")) or {}
        names = crd_spec.get("names") or {}
        kind = names.get("kind", "")
        validation = crd_spec.get("validation") or {}
        params_schema = validation.get("openAPIV3Schema")
        targets_raw = spec.get("targets")
        if targets_raw is None:
            raise InvalidTemplateError(
                'Field "targets" not specified in ConstraintTemplate spec'
            )
        if not isinstance(targets_raw, list) or len(targets_raw) == 0:
            raise InvalidTemplateError(
                "No targets specified. ConstraintTemplate must specify one target"
            )
        if len(targets_raw) > 1:
            raise InvalidTemplateError(
                "Multi-target templates are not currently supported"
            )
        targets = [
            TargetSpec(
                target=t.get("target", ""),
                rego=t.get("rego", ""),
                libs=list(t.get("libs") or []),
            )
            for t in targets_raw
        ]
        return cls(
            name=name,
            kind=kind,
            targets=targets,
            parameters_schema=params_schema,
            api_version=api_version,
            labels=dict(metadata.get("labels") or {}),
            raw=obj,
        )

    def validate_names(self) -> None:
        """client.go:245: template name must equal lowercase of CRD kind."""
        if self.name != self.kind.lower():
            raise InvalidTemplateError(
                f"Template's name {self.name} is not equal to the lowercase "
                f"of CRD's Kind: {self.kind.lower()}"
            )


@dataclass
class CRD:
    """CRD-lite for a constraint kind (crd_helpers.go:85-140)."""

    kind: str
    group: str = CONSTRAINT_GROUP
    plural: str = ""
    schema: Optional[Dict[str, Any]] = None

    @property
    def name(self) -> str:
        return f"{self.plural}.{self.group}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "apiextensions.k8s.io/v1beta1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": self.name},
            "spec": {
                "group": self.group,
                "names": {
                    "kind": self.kind,
                    "listKind": self.kind + "List",
                    "plural": self.plural,
                    "singular": self.plural,
                    "categories": ["constraint"],
                },
                "scope": "Cluster",
                "version": "v1beta1",
                "versions": [
                    {"name": "v1beta1", "served": True, "storage": True},
                    {"name": "v1alpha1", "served": True, "storage": False},
                ],
                "validation": {"openAPIV3Schema": self.schema},
                "subresources": {"status": {}},
            },
        }


def create_crd(
    templ: ConstraintTemplate, match_schema: Dict[str, Any]
) -> CRD:
    """createSchema + createCRD (crd_helpers.go:40-140)."""
    spec_props: Dict[str, Any] = {
        "match": match_schema,
        "enforcementAction": {"type": "string"},
    }
    if templ.parameters_schema is not None:
        spec_props["parameters"] = templ.parameters_schema
    schema = {
        "properties": {
            "metadata": {
                "properties": {
                    "name": {"type": "string", "maxLength": 63},
                }
            },
            "spec": {"properties": spec_props},
        }
    }
    return CRD(kind=templ.kind, plural=templ.kind.lower(), schema=schema)


def validate_constraint_against_crd(
    constraint: Dict[str, Any], crd: CRD
) -> None:
    """validateCR (crd_helpers.go: validateCR): group/kind agreement + schema."""
    api_version = constraint.get("apiVersion", "")
    group, _, _version = api_version.partition("/")
    if group != crd.group:
        raise InvalidConstraintError(
            f"Constraint group {group!r} does not match CRD group {crd.group!r}"
        )
    if constraint.get("kind") != crd.kind:
        raise InvalidConstraintError(
            f"Constraint kind {constraint.get('kind')!r} does not match CRD "
            f"kind {crd.kind!r}"
        )
    name = ((constraint.get("metadata") or {}).get("name")) or ""
    if name == "":
        raise InvalidConstraintError("Constraint has no name")
    errors = validate_json_schema(constraint, crd.schema, path="")
    if errors:
        raise InvalidConstraintError("; ".join(errors))


_TYPE_CHECKS = {
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
    "array": lambda v: isinstance(v, list),
    "object": lambda v: isinstance(v, dict),
    "null": lambda v: v is None,
}


def validate_json_schema(
    value: Any, schema: Optional[Dict[str, Any]], path: str = ""
) -> List[str]:
    """Validate `value` against a JSON-Schema subset; returns error strings.

    Permissive like v1beta1 CRD validation: unknown keys pass unless
    additionalProperties is explicitly false; absent fields only fail when
    listed in `required`; null values are skipped unless a type says
    otherwise (OpenAPI v3 has no union types here).
    """
    errs: List[str] = []
    if not isinstance(schema, dict):
        return errs
    loc = path or "<root>"
    typ = schema.get("type")
    if typ is not None and value is not None:
        check = _TYPE_CHECKS.get(typ)
        if check and not check(value):
            errs.append(f"{loc}: expected {typ}, got {type(value).__name__}")
            return errs
    enum = schema.get("enum")
    if isinstance(enum, list) and enum and value is not None and value not in enum:
        errs.append(f"{loc}: {value!r} not in enum {enum!r}")
    if isinstance(value, str):
        max_len = schema.get("maxLength")
        if isinstance(max_len, int) and len(value) > max_len:
            errs.append(f"{loc}: length {len(value)} exceeds maxLength {max_len}")
        pattern = schema.get("pattern")
        if isinstance(pattern, str):
            import re

            if not re.search(pattern, value):
                errs.append(f"{loc}: does not match pattern {pattern!r}")
    if isinstance(value, dict):
        required = schema.get("required")
        if isinstance(required, list):
            for req in required:
                if req not in value:
                    errs.append(f"{loc}: missing required field {req!r}")
        props = schema.get("properties")
        if isinstance(props, dict):
            for k, sub in props.items():
                if k in value:
                    errs.extend(
                        validate_json_schema(value[k], sub, f"{path}.{k}" if path else k)
                    )
        addl = schema.get("additionalProperties")
        if addl is False and isinstance(props, dict):
            for k in value:
                if k not in props:
                    errs.append(f"{loc}: unknown field {k!r}")
        elif isinstance(addl, dict):
            known = props or {}
            for k, v in value.items():
                if k not in known:
                    errs.extend(
                        validate_json_schema(v, addl, f"{path}.{k}" if path else k)
                    )
    if isinstance(value, list):
        items = schema.get("items")
        if isinstance(items, dict):
            for i, v in enumerate(value):
                errs.extend(validate_json_schema(v, items, f"{loc}[{i}]"))
        min_items = schema.get("minItems")
        if isinstance(min_items, int) and len(value) < min_items:
            errs.append(f"{loc}: fewer than minItems {min_items}")
    return errs
