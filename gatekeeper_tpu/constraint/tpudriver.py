"""TpuDriver: the compiled, batched evaluation engine behind the Driver
boundary.

This is the TPU counterpart of the reference's sole driver implementation
(vendor/.../frameworks/constraint/pkg/client/drivers/local/local.go:48-394,
behind drivers/interface.go:21-39). Where `local` answers every
`hooks[...].audit` query by interpreting one Rego cross-join over the whole
data cache, TpuDriver evaluates the same query as two fused device
dispatches over dense tensors:

  1. the constraint x resource **match matrix** (`engine/matchkernel.py`) —
     the vectorized form of `matching_constraints`
     (pkg/target/target_template_source.go:27-44), and
  2. the batch of **compiled template programs** (`engine/programs.py`) —
     per-(template, params) violation counters produced by the symbolic
     Rego compiler (`engine/symbolic.py`), all traced into one jitted
     callable.

Violating (constraint, resource) pairs come back as a sparse index set;
only those pairs are re-evaluated host-side with the interpreter to render
exact violation messages/details (violations are sparse in steady state,
so host work is O(violations), not O(C x N)).

Hybrid routing (the `Driver` boundary makes this natural — SURVEY §7
"hard parts"):
  * templates outside the compilable Rego subset raise
    `CompileUnsupported` at mount/first-use and are routed per-template to
    the interpreter (`RegoDriver._eval_template`), restricted to
    kernel-matched reviews;
  * resources whose array fanout exceeds the device bucket cap
    (`G_CAP`) are routed per-row to the interpreter, so EGroup's bounded
    fanout can never silently drop violations (fail-closed routing).

Bit-for-bit result parity with RegoDriver over the constraint-client
battery is enforced by tests/test_tpu_driver.py.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import (
    CODE_MISMATCH,
    INTERPRETER as V_INTERPRETER,
    VectorizabilityReport,
    analyze_modules,
)
from ..engine.matchkernel import matchspec_to_np
from ..faults import FaultError, device_point, fire
from ..engine.patterns import PatternRegistry, _match
from ..engine.programs import Program, ProgramEvaluator, compile_program
from ..engine.symbolic import CompilerEnv, CompileUnsupported
from ..engine.tables import StrTables
from ..flatten.encoder import (
    _bucket,
    batch_review_features,
    encode_token_table,
    mask_token_table,
    unesc_seg,
)
from ..flatten.vocab import Vocab
from ..rego import ast as A
from ..rego.interp import RegoError, Undefined, _call_function
from ..rego.values import freeze, thaw
from . import hooks as H
from .driver import (
    _HOOK_RE,
    RegoDriver,
    _autoreject_result,
    _cname,
    constraint_key,
)
from .types import Response, Result

_TEMPLATE_PREFIX_RE = re.compile(r'^templates\["([^"]+)"\]\["([^"]+)"\]$')

# Array-axis fanout cap for device evaluation. Objects with more than
# G_CAP elements on a lifted array axis (e.g. a pod with >G_CAP
# containers) are routed to the interpreter instead of being evaluated
# with truncated fanout (ADVICE r1: EGroup drops tokens with idx >= g).
G_CAP = 64

# Resource-axis chunk for device dispatch: bounds the [N, L, G]
# intermediates EGroup materializes and keeps one stable jit shape that
# every chunk (padded) reuses.
N_CHUNK = 32768

# Adaptive micro-batch routing: review batches below this size evaluate
# serially on the interpreter — a per-request interp review costs ~10ms
# while a fused device dispatch pays a fixed round trip (~100-200ms on a
# tunneled chip) plus encode/stage; large batches amortize it. Tunable
# per deployment via GATEKEEPER_TPU_MIN_DEVICE_BATCH (a locally-attached
# chip with ~1ms dispatch wants ~2; the tunneled bench chip wants ~12).
import os as _os
import threading as _threading

MIN_DEVICE_BATCH = int(
    _os.environ.get("GATEKEEPER_TPU_MIN_DEVICE_BATCH", "12")
)

# Pair-aware floor for SUBSET dispatches (pruned partitions): a mask-
# sliced sub-batch is small in reviews but DENSE in (review, constraint)
# pairs — the locality planner co-locates exactly the constraints those
# reviews match — so row count alone mis-routes it to the serial
# interpreter, which then pays every pair at interpreter cost. A subset
# batch below MIN_DEVICE_BATCH rows still takes the device when its
# review x constraint pair volume clears this floor.
MIN_DEVICE_PAIRS = int(
    _os.environ.get("GATEKEEPER_TPU_MIN_DEVICE_PAIRS", "256")
)


def _params_key(params: Any) -> str:
    return json.dumps(params, sort_keys=True, default=str)


_CACHE_ENABLED = False
# process-wide ProgramStore (gatekeeper_tpu/compile): the persistent
# XLA cache now lives behind the fingerprint gate — XLA only ever reads
# this machine's private per-fingerprint subdir, never a foreign blob
_STORE = None


def _enable_compile_cache():
    """Persistent XLA compilation cache: template ingest re-pays minutes
    of XLA compile per fresh process otherwise (the reference's
    interpreter has no compile step to amortize; this engine does).
    Opt out with GATEKEEPER_TPU_NO_COMPILE_CACHE=1; relocate with
    GATEKEEPER_TPU_COMPILE_CACHE_DIR.

    Routed through the content-addressed program store (docs/compile.md):
    the store root holds attested artifacts; XLA's cache dir is the
    store's by-fingerprint subdir, populated only with artifacts whose
    attested machine fingerprint matches this process — a cache volume
    shared across heterogeneous node pools can no longer feed XLA an
    AOT artifact compiled for a different ISA (the MULTICHIP_r05 SIGILL
    warning class). Returns the store (None = caching disabled)."""
    global _CACHE_ENABLED, _STORE
    if _CACHE_ENABLED:
        return _STORE
    _CACHE_ENABLED = True
    try:
        from ..compile import store_from_env

        store = store_from_env()
        if store is None:
            return None
        import jax

        jax.config.update("jax_compilation_cache_dir", store.xla_cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _STORE = store
    except Exception:
        _STORE = None  # cache is an optimization; never fail construction
    return _STORE


@dataclass
class _Corpus:
    """Encoded audit corpus, cached across sweeps until data changes."""

    data_gen: int
    reviews: List[Any]
    tok: Dict[str, np.ndarray]
    fb_dev: Dict[str, Any]
    g: int  # first-level array fanout bucket (idx0)
    row_fallback: np.ndarray  # [N] bool: route row to interpreter
    # second-level fanout bucket (idx1): mounts-per-container etc. are
    # typically tiny, and the g01 one-hot scales with g * g1 — bucketing
    # idx1 separately keeps it small (VERDICT perf watch-item)
    g1: int = 8
    # [(start, StagedBatch)] device-resident chunks; staged lazily at
    # first dispatch, reused every sweep until the corpus changes
    staged: Optional[List[Tuple[int, Any]]] = None
    # computed per-row screen features (invdup join bits), host copies
    row_feats: Optional[Dict[str, np.ndarray]] = None
    # per-pattern join-key value counts (pid -> (counts, has_fallback))
    value_counts: Optional[Dict[int, Any]] = None
    # ephemeral vocab overlay (webhook batches): the batch's novel
    # strings + their pattern/table rows, never interned globally
    vocab: Any = None  # OverlayVocab for ephemeral corpora, else None
    v_base: int = 0
    # provably-dead token slots dropped by the IR feature-liveness mask
    # before padding (analysis/ir.py); 0 when encoded keep-all
    skipped_static: int = 0
    ov_member: Optional[np.ndarray] = None  # [B_pad, P] bool
    ov_capture: Optional[np.ndarray] = None  # [B_pad, P] int32
    ov_tabs: Optional[Dict[str, np.ndarray]] = None  # name -> [B_pad]
    # external-data key extraction cache (feature name -> per-row
    # {provider -> keys} | None): keys are corpus-constant, but the
    # BITS derived from them track the live response cache and are
    # recomputed per dispatch (_extdata_row_bits)
    ext_keys: Optional[Dict[str, Any]] = None


@dataclass
class _ConstraintSet:
    """Compiled constraint-side tensors, cached until constraints change."""

    constraint_gen: int
    constraints: List[Dict[str, Any]]
    ms: Dict[str, np.ndarray]
    programs: List[Optional[Program]]  # index-aligned; None => fallback
    prog_rows: List[int]  # constraint index -> row in compiled stack (-1)
    policy: Optional[Any] = None  # StagedPolicy, device-resident
    # kind -> analyzer diagnostic code for interpreter-routed templates
    # (CODE_MISMATCH when the analyzer predicted compilable but the
    # compiler disagreed)
    fallback_codes: Dict[str, str] = None  # type: ignore[assignment]
    # content signature of this (sub)set's constraints + template IR
    # (docs/compile.md): a constraint-generation bump whose signature is
    # unchanged carries the staged policy forward instead of restaging
    signature: Optional[str] = None
    # IR feature-liveness over this set's programs (analysis/ir.py),
    # computed once per set: False = not yet computed, None = keep-all
    # (some program failed the pad-equivalence proof), frozenset = live
    # pattern indices
    live_pids: Any = False


class TpuDriver(RegoDriver):
    """Compiled-engine driver: device-batched audit/review, interpreter
    fallback for the uncompilable remainder."""

    def __init__(self, use_jax: bool = True, mesh=None, metrics=None):
        super().__init__()
        # fingerprint-gated program store (docs/compile.md); None when
        # caching is disabled (tests) or the store root is unwritable
        self.program_store = _enable_compile_cache() if use_jax else None
        # optional MetricsRegistry: per-template verdict gauges +
        # fallback-reason counters land here when wired (Runner calls
        # set_metrics; tests construct with metrics=)
        self.metrics = metrics
        # (target, kind) -> VectorizabilityReport, computed once per
        # mounted module set (the admission-time analyzer, re-run here
        # so the driver owns its routing decision even for modules
        # mounted without going through Client.add_template)
        self._analysis: Dict[Tuple[str, str], VectorizabilityReport] = {}
        # analyzer-says-compilable but CompileUnsupported raised: the
        # consistency assertion the old try/except routing became
        self.analyzer_mismatches = 0
        # (target, kind) -> diagnostic code for interpreter-routed
        # templates (machine-readable fallback reason)
        self._fallback_codes: Dict[Tuple[str, str], str] = {}
        self.vocab = Vocab()
        self.patterns = PatternRegistry(self.vocab)
        self.tables = StrTables(self.vocab)
        self.use_jax = use_jax
        self.evaluator = ProgramEvaluator(
            self.patterns, self.tables, use_jax=use_jax
        )
        if use_jax:
            from ..parallel.sharding import FusedAuditKernel

            self.kernel = FusedAuditKernel(
                self.patterns, self.tables, mesh=mesh
            )
            self.kernel.metrics = metrics  # compile/cache telemetry
        else:
            self.kernel = None
        # (target, kind) -> rewritten template modules
        self._kind_modules: Dict[Tuple[str, str], List[A.Module]] = {}
        # (target, kind, params_key) -> Program | None (None = fallback)
        self._programs: Dict[Tuple[str, str, str], Optional[Program]] = {}
        self._data_gen = 0
        self._constraint_gen = 0
        self._corpus: Dict[str, _Corpus] = {}  # per target
        self._cset: Dict[str, _ConstraintSet] = {}
        # partition-scoped constraint subsets (docs/robustness.md
        # §Fault domains): (target, frozenset of constraint keys) ->
        # independently staged/dispatchable _ConstraintSet. Bounded —
        # plan churn (quarantine/re-home) mints new subsets and the
        # stale ones must not pin device policy state forever.
        self._cset_sub: Dict[Tuple[str, frozenset], _ConstraintSet] = {}
        self._cset_sub_max = 64
        # rendered-pair cache for the persistent audit corpus: identical
        # (constraint, review, inventory) inputs render identical results,
        # so violating pairs that persist across sweeps skip the
        # interpreter re-render; invalidated wholesale on any data or
        # constraint generation change
        self._render_cache: Dict[
            str, Tuple[Tuple[int, int], Dict[Tuple[int, int], List[Result]]]
        ] = {}
        # render-cache bound (docs/robustness.md §soak): within one
        # (data, constraint) generation the pair space is corpus x
        # constraints — a huge synced corpus under sustained audit must
        # evict oldest-cached pairs, never grow without bound
        self.render_cache_max = int(
            _os.environ.get("GATEKEEPER_TPU_RENDER_CACHE_MAX", "65536")
        )
        self._render_cache_evictions = 0
        # instrumentation for tests/bench: compiled-path pair evaluations
        # vs interpreter fallback evaluations in the last query
        self.stats: Dict[str, int] = {}
        # serve-while-compiling (VERDICT r4 #4): the fused review path
        # serves only once its kernels are compiled for the current
        # constraint generation; until then device-sized batches route
        # to the interpreter and a background thread compiles, then the
        # route swaps atomically (the reference is Ready as soon as
        # state replays, pkg/readiness/ready_tracker.go:138-173 — its
        # interpreter has no compile step to hide)
        self._review_warm: Dict[str, int] = {}  # target -> constraint_gen
        self._warming: set = set()
        self._warm_lock = _threading.Lock()
        self.cold_batches = 0  # device-sized batches served cold (interp)
        self._render_errors = 0  # compiled-render bugs degraded to interp
        # derived-key prune render caches (uniqueserviceselector-style
        # joins): key index per data generation + oracle contexts
        self._prune_indexes: Dict[Tuple, Tuple[int, Any]] = {}
        self._prune_oracles: Dict[Tuple, Any] = {}
        self._hot_redispatches = 0  # chunks rerun for compaction overflow
        # externaldata.ExternalDataSystem (set_external_data): the
        # batch plane for external_data lookups — key prefetch per
        # micro-batch + the extdata row-feature screen
        self.external_data = None
        # obs.CostAttributor (set_attributor): per-constraint
        # device-time accounting — every dispatch's measured
        # device-execute window is apportioned over the constraints it
        # evaluated by the static cost model (_static_cost), labeled
        # with the partition that paid it (docs/observability.md
        # §Cost attribution)
        self.attributor = None
        # integrity.IntegrityPlane (set_integrity): canary rows packed
        # into each dispatch's padding slots + the golden swap gate
        # (docs/robustness.md §Verdict integrity). None = no canary
        # packing, no golden gate — the plain-driver test default.
        self.integrity = None
        # incremental compile plane (docs/compile.md): template IR
        # hashes + per-subset content signatures drive minimal
        # recompiles — churn restages only partitions whose signature
        # changed, and staged sub-programs swap atomically
        self._ir_hashes: Dict[Tuple[str, str], str] = {}
        self._sig_cache: Dict[Tuple[str, frozenset], Tuple[int, str]] = {}
        self._swap_gen = 0
        self.program_compiles = 0  # compile_program invocations
        self.subset_swaps = 0  # shadow sets atomically swapped live
        self.subset_carryforwards = 0  # gen bumps served by signature
        # IR static-analysis plane (analysis/ir.py): ephemeral review
        # batches encode under the constraint set's feature-liveness
        # mask, dropping token columns no compiled program can read
        # before padding. Disabled via env for parity audits; the
        # persistent audit corpus always encodes keep-all (it is cached
        # per DATA generation and must survive constraint churn).
        self.liveness_enabled = (
            _os.environ.get("GATEKEEPER_TPU_NO_STATIC_LIVENESS", "") == ""
        )
        self.columns_skipped_static = 0  # cumulative dead slots dropped
        self.liveness_batches = 0  # batches encoded under a live mask
        # target -> (constraint_gen, IrReport): lazily computed, pre-
        # populated across warm swaps by attach_ir_report
        self._ir_reports: Dict[str, Tuple[int, Any]] = {}

    # -- module/data bookkeeping (cache invalidation) ------------------------

    def put_modules(self, prefix: str, modules: Sequence[A.Module]) -> None:
        super().put_modules(prefix, modules)
        m = _TEMPLATE_PREFIX_RE.match(prefix)
        if m:
            target, kind = m.group(1), m.group(2)
            with self._mutex:
                self._kind_modules[(target, kind)] = list(modules)
                self._drop_programs(target, kind)

    def delete_modules(self, prefix: str) -> int:
        n = super().delete_modules(prefix)
        m = _TEMPLATE_PREFIX_RE.match(prefix)
        if m:
            target, kind = m.group(1), m.group(2)
            with self._mutex:
                self._kind_modules.pop((target, kind), None)
                self._drop_programs(target, kind)
        return n

    def _drop_programs(self, target: str, kind: str) -> None:
        for key in [k for k in self._programs if k[0] == target and k[1] == kind]:
            del self._programs[key]
        self._analysis.pop((target, kind), None)
        self._fallback_codes.pop((target, kind), None)
        self._ir_hashes.pop((target, kind), None)
        self._ir_reports.pop(target, None)
        for cache in (self._prune_oracles, self._prune_indexes):
            for key in [
                k for k in cache if k[0] == target and k[1] == kind
            ]:
                del cache[key]
        self._cset.pop(target, None)
        # a template (module) change produces new programs: the warm
        # flag keys on this generation, so bumping it here drops the
        # review route cold and the background re-warm loop proactively
        # compiles the NEW policy (without it, the stale flag left
        # re-warming to the first unlucky admission batch)
        self._constraint_gen += 1

    def put_data(self, path: str, data: Any) -> None:
        super().put_data(path, data)
        self._note_data_change(path)

    def delete_data(self, path: str) -> bool:
        existed = super().delete_data(path)
        self._note_data_change(path)
        return existed

    def _note_data_change(self, path: str) -> None:
        with self._mutex:
            p = path.lstrip("/")
            if p.startswith("external") or not p:
                self._data_gen += 1
            if p.startswith("constraints") or not p:
                self._constraint_gen += 1

    # -- program compilation -------------------------------------------------

    def _make_oracle(self, target: str, kind: str, params: Any):
        """Interpreter-backed helper-function oracle for the symbolic
        compiler: evaluates pure template helpers (canonify_cpu and
        friends) to build per-vocab-entry lookup tables.

        The package node and evaluation context are built once and
        reused across the whole table fill — the fill runs the oracle
        per vocab entry (hundreds of thousands of calls on a large
        corpus), and the shared context's function-result cache also
        memoizes the helpers' own inner calls (mem_multiple & co)."""
        node = self.interp._pkg_node(["templates", target, kind], create=False)
        if node is None:
            return lambda fn_name, value: (None, False)
        ctx = self.interp.make_context({"parameters": params}, {})

        def oracle_fn(fn_name: str, value: Any, extra=None):
            if extra is not None:
                # multi-arg tableized call: consts with the per-vocab
                # value substituted at the symbolic slot
                sym_idx, consts = extra
                call_args = [freeze(c) for c in consts]
                call_args[sym_idx] = freeze(value)
            else:
                call_args = [freeze(value)]
            try:
                v = _call_function(ctx, None, node, fn_name, call_args)
            except RegoError:
                return None, False
            if v is Undefined:
                return None, False
            return thaw(v), True

        return oracle_fn

    def set_external_data(self, system) -> None:
        """Wire the process's ExternalDataSystem (Runner/tests): the
        driver prefetches each batch's deduped keys through it and
        fills the extdata row-feature screens from its cache."""
        self.external_data = system

    def set_metrics(self, metrics) -> None:
        """Late metrics wiring (Runner builds its registry after the
        driver); also re-exports verdicts already analyzed."""
        self.metrics = metrics
        if self.kernel is not None:
            self.kernel.metrics = metrics
        if (
            self.program_store is not None
            and self.program_store.metrics is None
        ):
            self.program_store.metrics = metrics
        for (_t, kind), rep in self._analysis.items():
            self._export_verdict(kind, rep)

    def set_attributor(self, attributor) -> None:
        """Wire an obs.CostAttributor: from here on every dispatch's
        device-execute time is apportioned per constraint."""
        self.attributor = attributor

    def set_integrity(self, plane) -> None:
        """Wire an integrity.IntegrityPlane: from here on every fused
        dispatch packs canary rows into its padding slots and reports
        their verdict digests, and prepare_subset gates warm-swaps on
        a golden-batch replay (docs/robustness.md §Verdict integrity).
        """
        self.integrity = plane
        if plane is not None:
            plane.bind_driver(self)

    def _interp_closure(self, target: str, constraints):
        """Host-interpreter ground-truth closure for canary golden
        derivation: evaluates ONE review against exactly the constraint
        set the fused dispatch serves. Called under the serving mutex
        (reentrant) the first time a signature needs its golden set."""
        def interp(review):
            with self._mutex:
                return RegoDriver._violation(
                    self, target, {"review": review}, None,
                    constraints=list(constraints),
                )
        return interp

    def _canary_pack(
        self, target: str, sigkey: str, constraints, reviews,
        handler, ns_cache, rej_constraints,
    ):
        """Append up to K canary reviews into the padding slots this
        dispatch's shape bucket already wastes (the _stage_corpus
        padding formula — packing canaries never grows the staged
        shape, so the device cost is zero). Returns (reviews,
        canary_autorejects): the caller strips the canary tail from
        the eval split and reports it via _canary_check AFTER releasing
        the serving mutex. Canary autorejects mirror the live path so
        the digest compares against the interpreter golden, which
        includes autoreject results."""
        integ = self.integrity
        if integ is None:
            return reviews, []
        n = len(reviews)
        chunk = min(N_CHUNK, _bucket(n, lo=64))
        padded = chunk * -(-n // chunk)
        slots = padded - n
        if slots <= 0:
            return reviews, []
        try:
            canaries = integ.canaries_for(
                target, sigkey, constraints,
                self._interp_closure(target, constraints), slots,
            )
        except Exception:
            return reviews, []
        if not canaries:
            return reviews, []
        canary_autorej: List[List[Result]] = []
        for r in canaries:
            out: List[Result] = []
            if rej_constraints and handler.review_autorejects(r, ns_cache):
                out = [
                    _autoreject_result(c, r) for c in rej_constraints
                ]
            canary_autorej.append(out)
        return list(reviews) + list(canaries), canary_autorej

    def _canary_check(
        self, target: str, sigkey: str, device, split, canary_autorej,
        subset=None,
    ):
        """Digest-compare the stripped canary verdicts against the
        golden set (off the serving mutex — the plane may trip
        dispatcher quarantine)."""
        integ = self.integrity
        if integ is None or not canary_autorej:
            return
        try:
            integ.check_canaries(
                target, sigkey, device,
                [
                    auto + ev
                    for auto, ev in zip(canary_autorej, split)
                ],
                subset=subset,
            )
        except Exception:
            pass  # integrity accounting must never fail a dispatch

    @staticmethod
    def _static_cost(program) -> float:
        """Analyzer/compiler-derived static cost weight for one
        constraint: program expression rows (the compiled DAG's
        structural signature length plus its constant-tensor payload)
        × row-feature width (each per-row feature plane is another
        device-resident operand the dispatch streams). Interpreter-
        routed constraints (program None) weigh a flat 1 — they cost
        HOST time per matching pair; their device share should read
        ~0, but they must still appear in the table so the target list
        for pruning is complete."""
        if program is None:
            return 1.0
        rows = max(1, len(program.signature))
        consts = 0
        try:
            consts = sum(
                int(np.size(v)) for v in program.consts.values()
            )
        except Exception:
            pass
        width = 1 + len(program.row_features)
        return float((rows + consts) * width)

    def _attribute_dispatch(
        self, cs, device_seconds: float, partition
    ) -> None:
        """Feed one measured device-execute window to the attributor,
        apportioned over `cs`'s constraints by static weight. Called
        under the serving mutex — the attributor does dict math only."""
        if self.attributor is None or device_seconds <= 0.0:
            return
        try:
            entries = []
            for c, prog in zip(cs.constraints, cs.programs):
                meta = c.get("metadata") or {}
                entries.append((
                    str(c.get("kind", "")),
                    str(meta.get("name", "")),
                    self._static_cost(prog),
                ))
            self.attributor.note_dispatch(
                entries, device_seconds, partition=partition
            )
        except Exception:
            pass  # accounting must never fail a dispatch

    def attach_report(
        self, target: str, kind: str, report: VectorizabilityReport
    ) -> None:
        """Re-attach the admission-time analyzer report after a module
        swap. put_modules drops _analysis/_fallback_codes for the kind
        (warm-swap invalidation) and nothing repopulated them until the
        next dispatch lazily re-analyzed — so /readyz verdicts and the
        fallback-code table went blank under churn. Client.add_template
        hands its already-computed report straight back so the verdict
        (and its routing provenance) survives the recompile window."""
        if report is None:
            return
        with self._mutex:
            self._analysis[(target, kind)] = report
            if not report.compilable:
                self._fallback_codes[(target, kind)] = (
                    report.primary_code() or "GK-V007"
                )
        self._export_verdict(kind, report)

    def template_report(
        self, target: str, kind: str
    ) -> Optional[VectorizabilityReport]:
        """The analyzer's verdict for a mounted template (None when the
        kind has no modules mounted). Computed once per module set."""
        key = (target, kind)
        rep = self._analysis.get(key)
        if rep is None:
            mods = self._kind_modules.get(key)
            if mods is None:
                return None
            rep = analyze_modules(kind, mods)
            self._analysis[key] = rep
            self._export_verdict(kind, rep)
        return rep

    def _export_verdict(self, kind: str, rep: VectorizabilityReport):
        if self.metrics is None:
            return
        self.metrics.gauge(
            "template_vectorization", 1, kind=kind, verdict=rep.verdict
        )
        for code in rep.codes:
            n = sum(1 for d in rep.diagnostics if d.code == code)
            self.metrics.gauge(
                "template_analysis_diagnostics", n, kind=kind, code=code
            )

    def _note_fallback(self, kind: str, code: str) -> None:
        if self.metrics is not None:
            self.metrics.record(
                "template_fallback_total", 1, kind=kind, code=code
            )

    def _count(self, name: str, value: float = 1, **tags) -> None:
        """Counter increment alongside the in-object stat counters —
        the Prometheus view of cold_batches/_hot_redispatches/
        _render_errors, incremented at the same sites."""
        if self.metrics is not None:
            self.metrics.record(name, value, **tags)

    def _render_cache_put(
        self, cache: Dict[Tuple[int, int], List[Result]],
        key: Tuple[int, int], results: List[Result],
    ) -> None:
        """Bounded insert into a per-target rendered-pair cache:
        oldest-cached pair evicted (dict insertion order) when the
        bound is hit, counted so a soak's leak check can distinguish a
        bounded churning cache from a growing one."""
        if len(cache) >= self.render_cache_max:
            cache.pop(next(iter(cache)), None)
            self._render_cache_evictions += 1
            self._count("driver_render_cache_evictions_total")
        cache[key] = results

    def render_cache_size(self) -> int:
        """Total cached rendered pairs across targets (soak sampling)."""
        return sum(len(c[1]) for c in self._render_cache.values())

    def _program_for(
        self, target: str, constraint: Dict[str, Any]
    ) -> Optional[Program]:
        kind = constraint.get("kind")
        if not isinstance(kind, str):
            return None
        mods = self._kind_modules.get((target, kind))
        if mods is None:
            return None
        params = H.constraint_parameters(constraint)
        key = (target, kind, _params_key(params))
        if key in self._programs:
            return self._programs[key]
        # verdict-first routing: the static analyzer decides whether
        # compilation is even attempted. INTERPRETER/INVALID templates
        # route immediately with their diagnostic code; for templates
        # the analyzer calls compilable, CompileUnsupported is no
        # longer a routing mechanism — it is a counted consistency
        # failure (analyzer promised compilability).
        report = self.template_report(target, kind)
        if report is not None and not report.compilable:
            code = report.primary_code() or "GK-V007"
            self._fallback_codes[(target, kind)] = code
            self._note_fallback(kind, code)
            self._programs[key] = None
            return None
        extdata_feature = None
        if report is not None:
            mode = getattr(report, "extdata_mode", lambda: None)()
            if mode is not None:
                # feature encoding consumed by _extdata_row_bits:
                # extdata:<kind>:<err|all>
                extdata_feature = f"extdata:{kind}:{mode}"
        env = CompilerEnv(
            self.vocab,
            self.patterns,
            self.tables,
            oracle_fn=self._make_oracle(target, kind, params),
            oracle_ns=f"{kind}|{key[2]}",
            oracle_ns_shared=f"{target}|{kind}",
            template_kind=kind,
            extdata_feature=extdata_feature,
        )
        # an actual compile is happening: nothing in memory covered this
        # (target, kind, params) — the plan-diff battery asserts churn
        # of N kinds pays exactly N of these
        self.program_compiles += 1
        self._count("program_store_compiles_total", kind=kind)
        if self.program_store is not None:
            self.program_store.note_miss()
        try:
            prog = compile_program(env, mods, params)
        except CompileUnsupported as e:
            # consistency assertion: analyzer-vs-compiler disagreement
            # is a bug signal, surfaced via counter + metric + log
            self.analyzer_mismatches += 1
            self._fallback_codes[(target, kind)] = CODE_MISMATCH
            self._note_fallback(kind, CODE_MISMATCH)
            if self.metrics is not None:
                self.metrics.record(
                    "analyzer_compile_mismatch_total", 1, kind=kind
                )
            import logging

            logging.getLogger("gatekeeper_tpu.analysis").warning(
                "analyzer/compiler disagreement: %s predicted "
                "compilable but compilation gave up: %s",
                kind,
                e,
            )
            prog = None
        self._programs[key] = prog
        return prog

    # -- constraint-side tensors ---------------------------------------------

    def _constraint_set(self, target: str) -> Optional[_ConstraintSet]:
        cs = self._cset.get(target)
        if cs is not None and cs.constraint_gen == self._constraint_gen:
            return cs
        constraints = self._constraints(target)
        if not constraints:
            self._cset.pop(target, None)
            return None
        ms = self._handler(target).compile_match_specs(
            constraints, self.vocab
        )
        programs = [self._program_for(target, c) for c in constraints]
        # evict programs for (kind, params) pairs no longer referenced by
        # any live constraint — param churn must not accumulate programs
        live = {
            (target, c.get("kind"), _params_key(H.constraint_parameters(c)))
            for c in constraints
        }
        for key in [
            k for k in self._programs if k[0] == target and k not in live
        ]:
            del self._programs[key]
        prog_rows: List[int] = []
        row = 0
        for p in programs:
            if p is None:
                prog_rows.append(-1)
            else:
                prog_rows.append(row)
                row += 1
        fallback_codes = {
            c["kind"]: self._fallback_codes.get((target, c["kind"]))
            for c, p in zip(constraints, programs)
            if p is None and isinstance(c.get("kind"), str)
        }
        cs = _ConstraintSet(
            constraint_gen=self._constraint_gen,
            constraints=constraints,
            ms=matchspec_to_np(ms),
            programs=programs,
            prog_rows=prog_rows,
            fallback_codes={
                k: v or "GK-V007" for k, v in fallback_codes.items()
            },
        )
        self._cset[target] = cs
        return cs

    def constraint_generation(self) -> int:
        return self._constraint_gen

    def constraint_costs(self, target: str) -> Dict[str, float]:
        """Static-cost planner weights: the compiled program's analyzer
        cost (see _static_cost) per constraint key. Lazily compiles via
        the shared `_programs` cache, so a warm driver pays nothing and
        a cold one pays the compile it would pay on first dispatch
        anyway. The partition planner blends these with measured
        attributor seconds when available."""
        with self._mutex:
            return {
                constraint_key(c): self._static_cost(
                    self._program_for(target, c)
                )
                for c in self._constraints(target)
            }

    # -- incremental compile plane (docs/compile.md) -------------------------

    def _ir_hash(self, target: str, kind: str) -> str:
        """Content hash of a template's rewritten IR modules. AST nodes
        are plain dataclasses, so repr() is a stable structural
        rendering; memoized until put/delete_modules drops the kind."""
        key = (target, kind)
        h = self._ir_hashes.get(key)
        if h is None:
            mods = self._kind_modules.get(key)
            h = (
                hashlib.sha256(repr(mods).encode()).hexdigest()[:16]
                if mods is not None
                else ""
            )
            self._ir_hashes[key] = h
        return h

    def _subset_signature(self, target: str, subset: frozenset) -> str:
        """Content signature of one partition's sub-program: per member
        constraint, (key, template IR hash, constraint payload), plus
        the store's machine fingerprint. Two constraint generations
        with equal signatures stage byte-identical sub-programs, which
        is what licenses the carry-forward (no restage, no recompile).
        Memoized per constraint generation (caller holds the mutex)."""
        key = (target, subset)
        hit = self._sig_cache.get(key)
        if hit is not None and hit[0] == self._constraint_gen:
            return hit[1]
        parts = []
        for c in self._constraints(target):
            ck = constraint_key(c)
            if ck not in subset:
                continue
            kind = c.get("kind")
            parts.append((
                ck,
                self._ir_hash(
                    target, kind if isinstance(kind, str) else ""
                ),
                json.dumps(c, sort_keys=True, default=str),
            ))
        parts.sort()
        fp = (
            self.program_store.fp_digest
            if self.program_store is not None
            else ""
        )
        sig = hashlib.sha256(
            json.dumps([fp, parts]).encode()
        ).hexdigest()[:16]
        if len(self._sig_cache) >= 4 * self._cset_sub_max:
            self._sig_cache.pop(next(iter(self._sig_cache)), None)
        self._sig_cache[key] = (self._constraint_gen, sig)
        return sig

    def subset_signature(self, target: str, subset) -> str:
        """Public (dispatcher-facing) form of `_subset_signature`."""
        with self._mutex:
            return self._subset_signature(target, frozenset(subset))

    def subset_ready(self, target: str, subset) -> bool:
        """True when `subset`'s sub-program can serve a fused dispatch
        RIGHT NOW without compiling or staging: its constraint set is
        cached with a staged policy and its content signature matches
        the current constraint corpus. Drivers without a device kernel
        have nothing to stage and are always ready. The dispatcher uses
        this to decide sync vs background restage (docs/compile.md)."""
        if not self.use_jax or self.kernel is None:
            return True
        with self._mutex:
            fs = frozenset(subset)
            cs = self._cset_sub.get((target, fs))
            if cs is None or cs.policy is None:
                return False
            if cs.constraint_gen == self._constraint_gen:
                return True
            return (
                cs.signature is not None
                and cs.signature == self._subset_signature(target, fs)
            )

    def swap_generation(self) -> int:
        """Monotonic count of atomic sub-program swaps (prepare_subset
        landing a shadow set live) — /debug/programs surfaces it."""
        return self._swap_gen

    def compile_plane_stats(self) -> Dict[str, Any]:
        """Compile-plane counters + program-store view, the driver side
        of /debug/programs and the compile_storm flight record."""
        with self._mutex:
            out: Dict[str, Any] = {
                "constraint_generation": self._constraint_gen,
                "swap_generation": self._swap_gen,
                "program_compiles": self.program_compiles,
                "subset_swaps": self.subset_swaps,
                "subset_carryforwards": self.subset_carryforwards,
                "analyzer_mismatches": self.analyzer_mismatches,
            }
        store = self.program_store
        if store is not None:
            out["store"] = store.stats()
        return out

    def _subset_cset(
        self, target: str, subset: frozenset
    ) -> Optional[_ConstraintSet]:
        """Partition-scoped _ConstraintSet: only `subset`'s constraints,
        with its own match tensors and (lazily staged) device policy —
        the independently compilable/dispatchable sub-program behind one
        fault domain. Programs come from the shared `_programs` cache
        (a subset never re-compiles what the monolith compiled), and —
        unlike `_constraint_set` — no program eviction runs here: the
        subset view must never evict programs the full set still uses.

        Generation bumps whose content signature is unchanged carry the
        cached set (and its staged policy) forward instead of
        rebuilding: churn elsewhere in the corpus costs THIS partition
        nothing (docs/compile.md)."""
        key = (target, subset)
        cs = self._cset_sub.get(key)
        if cs is not None and cs.constraint_gen == self._constraint_gen:
            return cs
        sig = self._subset_signature(target, subset)
        if cs is not None and cs.signature is not None and cs.signature == sig:
            cs.constraint_gen = self._constraint_gen
            self.subset_carryforwards += 1
            self._count("program_carryforward_total", target=target)
            return cs
        cs = self._build_subset_cset(target, subset, sig)
        if cs is None:
            self._cset_sub.pop(key, None)
            return None
        while len(self._cset_sub) >= self._cset_sub_max:
            self._cset_sub.pop(next(iter(self._cset_sub)), None)
        self._cset_sub[key] = cs
        return cs

    def _build_subset_cset(
        self, target: str, subset: frozenset, sig: Optional[str] = None
    ) -> Optional[_ConstraintSet]:
        """Construct (but do NOT cache) a subset constraint set — the
        shared builder behind `_subset_cset` and `prepare_subset`'s
        shadow slot, which must never replace the live entry before its
        policy is staged."""
        constraints = [
            c for c in self._constraints(target)
            if constraint_key(c) in subset
        ]
        if not constraints:
            return None
        ms = self._handler(target).compile_match_specs(
            constraints, self.vocab
        )
        programs = [self._program_for(target, c) for c in constraints]
        prog_rows: List[int] = []
        row = 0
        for p in programs:
            if p is None:
                prog_rows.append(-1)
            else:
                prog_rows.append(row)
                row += 1
        fallback_codes = {
            c["kind"]: self._fallback_codes.get((target, c["kind"]))
            for c, p in zip(constraints, programs)
            if p is None and isinstance(c.get("kind"), str)
        }
        return _ConstraintSet(
            constraint_gen=self._constraint_gen,
            constraints=constraints,
            ms=matchspec_to_np(ms),
            programs=programs,
            prog_rows=prog_rows,
            fallback_codes={
                k: v or "GK-V007" for k, v in fallback_codes.items()
            },
            signature=(
                sig
                if sig is not None
                else self._subset_signature(target, subset)
            ),
        )

    # -- corpus encoding -----------------------------------------------------

    def _encode_reviews(
        self,
        target: str,
        reviews: List[Any],
        ns_cache: Dict[str, Any],
        vocab: Any = None,
        keep_fn: Optional[Callable[[int], bool]] = None,
    ) -> Tuple[
        Dict[str, np.ndarray], Dict[str, Any], int, np.ndarray, int
    ]:
        """`vocab` overrides the intern target — ephemeral review batches
        pass an OverlayVocab so batch churn never grows the base.
        Review-feature extraction is the target handler's (the K8s and
        agent targets share the engine encoding via their IR reviews).

        `keep_fn` (spath vocab id -> bool) is the IR feature-liveness
        mask: provably-dead token columns are dropped and survivors
        compacted BEFORE the L/G bucketing below, so padding and the
        one-hot group contraction shrink with the live set. Overflow is
        decided by the unfiltered encode (a truncated row already lost
        arbitrary live tokens and must keep routing to the
        interpreter); everything downstream sees only the filtered
        table, so fewer G_CAP clips after filtering is strictly more
        fused coverage, never a verdict change."""
        if vocab is None:
            vocab = self.vocab
        handler = self._handler(target)
        table = encode_token_table(reviews, vocab)
        skipped = 0
        if keep_fn is not None:
            table, skipped = mask_token_table(table, keep_fn)
            if skipped:
                self.columns_skipped_static += skipped
                self.liveness_batches += 1
                self._count(
                    "columns_skipped_static_total", skipped, target=target
                )
        feats = [
            handler.encode_review_features(r, ns_cache, vocab)
            for r in reviews
        ]
        fb = batch_review_features(feats)
        tok = {
            "spath": table.spath,
            "idx0": table.idx0,
            "idx1": table.idx1,
            "kind": table.kind,
            "vid": table.vid,
            "vnum": table.vnum,
        }
        max_i0 = int(np.asarray(table.idx0).max(initial=-1))
        max_i1 = int(np.asarray(table.idx1).max(initial=-1))
        g = _bucket(max(max_i0 + 1, 1), lo=8)
        g1 = _bucket(max(max_i1 + 1, 1), lo=4)
        row_fallback = np.asarray(table.overflow).copy()
        if fb.label_overflow is not None:
            row_fallback |= fb.label_overflow
        if g > G_CAP:
            g = G_CAP
            row_fallback |= (table.idx0 >= G_CAP).any(axis=1)
        if g1 > G_CAP:
            g1 = G_CAP
            row_fallback |= (table.idx1 >= G_CAP).any(axis=1)
        return tok, _features_np(fb), (g, g1), row_fallback, skipped

    def _audit_corpus(self, target: str) -> Optional[_Corpus]:
        corpus = self._corpus.get(target)
        if corpus is not None and corpus.data_gen == self._data_gen:
            return corpus
        external = self.storage.get(["external", target], {})
        reviews = list(self._handler(target).iter_cached_reviews(external))
        if not reviews:
            self._corpus.pop(target, None)
            return None
        ns_cache = self._ns_cache(target)
        tok, fb_dev, (g, g1), row_fallback, _ = self._encode_reviews(
            target, reviews, ns_cache
        )
        corpus = _Corpus(
            data_gen=self._data_gen,
            reviews=reviews,
            tok=tok,
            fb_dev=fb_dev,
            g=g,
            g1=g1,
            row_fallback=row_fallback,
        )
        # classify the freshly interned path entries NOW: callers probe
        # membership (_pattern_tokens) straight after building the corpus
        self.patterns.sync()
        self.tables.sync()
        self._corpus[target] = corpus
        return corpus

    def _ephemeral_corpus(
        self,
        target: str,
        cs: _ConstraintSet,
        reviews: List[Any],
        ns_cache: Dict[str, Any],
        coarse_feats: bool = False,
    ) -> _Corpus:
        """Encode a review batch against an OverlayVocab and build its
        pattern/table overlay blocks. The base vocab, patterns, and
        tables never change, so steady-state admission pays no global
        table growth, no device re-uploads, and no jit churn — the
        batch ships its own few-hundred-row overlay instead.

        coarse_feats=True (warm path) skips the audit-corpus pre-encode
        that inventory-screen row features normally force — the warmup
        dispatch only needs the right SHAPES, so it uses all-ones
        (route-everything, sound) feature bits instead of stalling the
        serving mutex on a full corpus encode."""
        from ..flatten.vocab import OverlayVocab

        # base must be at its fixed point BEFORE the overlay snapshot,
        # or overlay ids alias base ids assigned later in this call.
        # Inventory-screen row features encode the persistent audit
        # corpus mid-evaluation — pre-encode it now if any program will
        # need it (cached per data generation, so this is one-time).
        if not coarse_feats and any(
            p is not None and p.row_features for p in cs.programs
        ):
            self._audit_corpus(target)
        self.patterns.sync()
        self.tables.sync()
        overlay = OverlayVocab(self.vocab)
        keep_fn = self._liveness_keep_fn(cs, overlay)
        tok, fb_dev, (g, g1), row_fallback, skipped = self._encode_reviews(
            target, reviews, ns_cache, vocab=overlay, keep_fn=keep_fn
        )
        v_base = overlay.base_len
        # fill table rows + pattern rows for overlay entries to a fixed
        # point (transforms and captured segments intern new overlay
        # strings as they go)
        tab_parts: List[Dict[str, np.ndarray]] = []
        mem_parts: List[np.ndarray] = []
        cap_parts: List[np.ndarray] = []
        cur = v_base
        while cur < len(overlay):
            end = len(overlay)
            tab_parts.append(self.tables.fill_overlay(overlay, cur, end))
            m, c = self.patterns.classify_overlay(overlay, cur, end)
            mem_parts.append(m)
            cap_parts.append(c)
            cur = end
        b = len(overlay) - v_base
        b_pad = _bucket(max(b, 1), lo=128)
        p = self.patterns.n_patterns
        ov_member = np.zeros((b_pad, p), bool)
        ov_capture = np.full((b_pad, p), -1, np.int32)
        if b:
            ov_member[:b] = np.concatenate(mem_parts, axis=0)
            ov_capture[:b] = np.concatenate(cap_parts, axis=0)
        ov_tabs: Dict[str, np.ndarray] = {}
        if tab_parts and tab_parts[0]:
            for name in tab_parts[0]:
                col = np.concatenate([t[name] for t in tab_parts])
                padded = np.zeros((b_pad,), col.dtype)
                padded[:b] = col
                ov_tabs[name] = padded
        return _Corpus(
            data_gen=-1,
            reviews=reviews,
            tok=tok,
            fb_dev=fb_dev,
            g=g,
            g1=g1,
            row_fallback=row_fallback,
            vocab=overlay,
            v_base=v_base,
            ov_member=ov_member,
            ov_capture=ov_capture,
            ov_tabs=ov_tabs,
            skipped_static=skipped,
        )

    # -- IR static-analysis plane (analysis/ir.py) ---------------------------

    def _cs_live_pids(self, cs: _ConstraintSet) -> Optional[frozenset]:
        """Live pattern indices over this set's compiled programs,
        computed once per set and cached on it. None means keep-all:
        some program failed the pad-equivalence proof (or the analysis
        itself failed — refuse, never guess)."""
        if cs.live_pids is False:
            from ..analysis.ir import corpus_liveness

            try:
                cs.live_pids = corpus_liveness(cs.programs)
            except Exception:
                cs.live_pids = None
        return cs.live_pids

    def _liveness_keep_fn(
        self, cs: _ConstraintSet, vocab: Any
    ) -> Optional[Callable[[int], bool]]:
        """Token keep-predicate for encoding a batch that only this
        set's programs will read: spath vocab id -> does the path match
        ANY live pattern. None disables filtering (liveness off, or the
        set is not provably maskable). Subset sets get their own
        (tighter) mask — each subset dispatch encodes its own ephemeral
        corpus, so set-scoped liveness is sound."""
        if not self.liveness_enabled:
            return None
        live = self._cs_live_pids(cs)
        if live is None:
            return None
        pat_segs = [self.patterns.segs(p) for p in sorted(live)]
        memo: Dict[int, bool] = {}

        def keep(pid: int) -> bool:
            hit = memo.get(pid)
            if hit is None:
                s = vocab.string(pid)
                if isinstance(s, str) and s.startswith("p:"):
                    segs = s[2:].split(".")
                    hit = any(_match(ps, segs)[0] for ps in pat_segs)
                else:
                    hit = True  # not a path entry: refuse to drop
                memo[pid] = hit
            return hit

        return keep

    def liveness_stats(self) -> Dict[str, Any]:
        """Liveness-plane counters, the driver side of decision facts
        and /debug/partitions."""
        return {
            "enabled": self.liveness_enabled,
            "columns_skipped_static": self.columns_skipped_static,
            "liveness_batches": self.liveness_batches,
        }

    def ir_report(self, target: str):
        """IR static-analysis report (analysis/ir.py IrReport) over the
        target's current compiled constraint set: GK-P0xx diagnostics,
        fused-path taxonomy, liveness summary, and specialization
        certificates. Lazily computed once per constraint generation;
        attach_ir_report pre-populates across warm swaps (the
        attach_report contract)."""
        ent = self._ir_reports.get(target)
        if ent is not None and ent[0] == self._constraint_gen:
            return ent[1]
        from ..analysis.ir import ir_from_programs

        with self._mutex:
            cs = self._constraint_set(target)
            if cs is None:
                return None
            gen = self._constraint_gen
            items = []
            for c, prog in zip(cs.constraints, cs.programs):
                kind = c.get("kind")
                name = (c.get("metadata") or {}).get("name", "")
                items.append(
                    (
                        f"constraint:{kind}/{name}",
                        kind,
                        prog,
                        H.constraint_parameters(c),
                    )
                )
            rep = ir_from_programs(items, fallback_codes=cs.fallback_codes)
            rep.liveness["patterns_total"] = self.patterns.n_patterns
            self._ir_reports[target] = (gen, rep)
        return rep

    def attach_ir_report(self, target: str, report: Any) -> None:
        """Re-attach an already-computed IR report after a module swap,
        so the IR plane (stats.analysis.ir, /debug views) never goes
        blank under churn — the attach_report contract."""
        if report is None:
            return
        with self._mutex:
            self._ir_reports[target] = (self._constraint_gen, report)

    # -- device dispatch -----------------------------------------------------

    def _stage_corpus(self, corpus: _Corpus):
        """Slice/pad the encoded corpus into uniform fixed-shape chunks,
        stack them on a leading chunk axis, and ship to device once
        (StackedCorpus); sweeps then run as ONE device execution against
        resident operands — no host->device traffic and a single
        round-trip in steady state."""
        if corpus.staged is not None:
            return corpus.staged
        n = len(corpus.reviews)
        chunk = min(N_CHUNK, _bucket(n, lo=64))
        if self.metrics is not None:
            # device-batch shape telemetry: bucketed chunk shapes trade
            # padded rows for jit-shape stability — occupancy % and
            # waste rows quantify what that trade costs per staging
            padded = chunk * -(-n // chunk)
            path = "audit" if corpus.data_gen >= 0 else "webhook"
            self.metrics.observe(
                "batch_occupancy_percent", 100.0 * n / padded, path=path
            )
            self.metrics.record(
                "padding_waste_rows_total", padded - n, path=path
            )
        chunks = []
        for start in range(0, n, chunk):
            end = min(start + chunk, n)
            pad = chunk - (end - start)
            fb_c = {
                k: _pad_rows(v[start:end], pad)
                for k, v in corpus.fb_dev.items()
            }
            tok_c = {
                k: _pad_rows(v[start:end], pad, fill=0 if k == "vnum" else -1)
                for k, v in corpus.tok.items()
            }
            chunks.append(
                (fb_c, tok_c, corpus.row_fallback[start:end], end - start)
            )
        ov = None
        if corpus.ov_member is not None:
            ov = {
                "member": corpus.ov_member,
                "capture": corpus.ov_capture,
                "tabs": corpus.ov_tabs,
            }
        corpus.staged = self.kernel.stage_corpus_stacked(
            chunks, ov=ov, v_base=corpus.v_base
        )
        return corpus.staged

    def _need_pairs(
        self, target: str, cs: _ConstraintSet, corpus: _Corpus,
        require_compiled: bool = False,
    ) -> Tuple[List[Tuple[int, int]], int, int]:
        """Sparse evaluation: -> (review-major (n, c) pairs needing
        interpreter work, compiled_pairs, interp_pairs). With
        require_compiled, raises ColdKernel instead of compiling a
        missing (policy, shape-bucket) jit entry."""
        if cs.policy is None:
            cs.policy = self.kernel.stage_policy(cs.programs, cs.ms)
        policy = cs.policy
        from ..parallel.sharding import decode_need

        stacked = self._stage_corpus(corpus)
        needed = sorted(
            {
                f
                for p in cs.programs
                if p is not None
                for f in p.row_features
            }
        )
        if needed:
            feats = self._row_feature_bits(target, corpus, needed)
            self.kernel.stage_row_feats(
                stacked, feats,
                volatile=[n for n in needed if n.startswith("extdata:")],
            )
        # named fault point (docs/robustness.md): "error" simulates a
        # failing device dispatch, "hang" a stalled one — exercised by
        # the chaos suite to drive the real degradation ladder
        fire("driver.device_dispatch")
        # the whole sweep: one device execution, one fetch
        packed, hot, n_hot, sc, si = self.kernel.dispatch_need_all(
            policy, stacked, (corpus.g, corpus.g1),
            require_compiled=require_compiled,
        )
        pairs: List[Tuple[int, int]] = []
        stat_c = int(sc.sum())
        stat_i = int(si.sum())
        for ci in range(stacked.k):
            start = ci * stacked.chunk
            if int(n_hot[ci]) > hot.shape[1]:
                # more violating rows than the compaction window: rare
                # (adversarial corpora); re-dispatch this chunk alone
                p_c, h_c, _nh, _sc, _si = self._redispatch_chunk(
                    policy, corpus, stacked, ci, int(n_hot[ci]),
                    require_compiled=require_compiled,
                )
                n_loc, c_is = decode_need(p_c, h_c, policy.c_pad)
            else:
                n_loc, c_is = decode_need(
                    packed[ci], hot[ci], policy.c_pad
                )
            pairs.extend(zip((start + n_loc).tolist(), c_is.tolist()))
        return pairs, stat_c, stat_i

    def _row_feature_bits(
        self, target: str, corpus: _Corpus, names: List[str]
    ) -> Dict[str, np.ndarray]:
        """Per-row screen refinement bits for inventory join templates.

        "invdup:<leaf>:<mirror>:<se>:<guards>" semantics (sound
        over-approximations of the join truth; encoding produced by
        symbolic.Compiler._compile_clause):
          * the row's candidate values are its tokens at the LEAF
            pattern; partners are counted at the MIRROR pattern (the
            partner-side path proved by symbolic._mirror_pattern_for —
            same pattern for self-joins, a "?"-generalized one when the
            inventory walk iterates vars);
          * persistent audit corpus (reviews ARE the inventory): a
            value carried by >=2 distinct rows at the mirror pattern
            can conflict. The threshold 2 is only sound when <se>=1 (a
            proven `not identical(obj, input.review)` guard excludes
            the self-partner) AND the row carries every <guards>
            identity field the proof needs (rows missing one can join
            themselves); otherwise the threshold drops to 1;
          * ephemeral review batch (webhook): the row holds a value at
            the leaf pattern present ANYWHERE in the synced inventory
            at the mirror pattern (exclusions re-checked exactly by the
            interpreter render).
        """
        if corpus.row_feats is None:
            corpus.row_feats = {}
        out: Dict[str, np.ndarray] = {}
        # alias guard: if the BASE vocab grew past an ephemeral corpus's
        # overlay snapshot (a path _ephemeral_corpus's pre-encode did not
        # anticipate), overlay ids numerically collide with the new base
        # ids and every id comparison below is unsound — degrade to the
        # coarse screen (route everything) instead of guessing
        if corpus.vocab is not None and len(self.vocab) > corpus.v_base:
            ones = np.ones(len(corpus.reviews), bool)
            return {name: ones for name in names}
        for name in names:
            if name.startswith("extdata:"):
                # never cached in row_feats: the bits track the LIVE
                # response cache (TTL expiry between sweeps must route
                # rows back to the interpreter re-check)
                out[name] = self._extdata_row_bits(target, corpus, name)
                continue
            cached = corpus.row_feats.get(name)
            if cached is not None:
                out[name] = cached
                continue
            parts = name.split(":")
            leaf_pid, mirror_pid = int(parts[1]), int(parts[2])
            self_excl = parts[3] == "1"
            gpids = [int(x) for x in parts[4].split("+") if x]
            base = corpus
            if corpus.data_gen >= 0:
                counts, inv_fb = self._pattern_value_counts(
                    corpus, mirror_pid
                )
                # a fallback (token-overflow) row's keys are invisible
                # to the counts: its partner would see count 1 — drop
                # the threshold so single-count carriers still route
                thresh = 2 if (self_excl and not inv_fb) else 1
            else:
                with_inv = self._audit_corpus(target)
                if with_inv is None:
                    counts, inv_fb = None, False
                else:
                    counts, inv_fb = self._pattern_value_counts(
                        with_inv, mirror_pid
                    )
                thresh = 1
            sel, vids = self._pattern_tokens(base, leaf_pid)
            if counts is None:
                feat = np.zeros(len(base.reviews), bool)
            elif inv_fb and corpus.data_gen < 0:
                # inventory keys partially invisible: reviews cannot be
                # screened against it — route everything (coarse, sound)
                feat = np.ones(len(base.reviews), bool)
            else:
                dup = counts >= thresh
                safe = np.minimum(np.maximum(vids, 0), dup.shape[0] - 1)
                hit = sel & (vids >= 0) & (vids < dup.shape[0]) & dup[safe]
                feat = hit.any(axis=1)
                if corpus.data_gen >= 0 and thresh >= 2 and gpids:
                    # rows missing a guard identity field void the
                    # self-exclusion proof: keep them routed
                    has_all = np.ones(len(base.reviews), bool)
                    for gp in gpids:
                        gsel, gvids = self._pattern_tokens(base, gp)
                        has_all &= (gsel & (gvids >= 0)).any(axis=1)
                    feat |= ~has_all
            # fallback rows (overflow etc.) must stay routed
            feat |= np.asarray(base.row_fallback, bool)
            corpus.row_feats[name] = feat
            out[name] = feat
        return out

    def _pattern_tokens(self, corpus: _Corpus, pid: int):
        member = np.asarray(self.patterns.member)
        spath = corpus.tok["spath"]
        vids = corpus.tok["vid"]
        width = member.shape[1]
        safe = np.minimum(np.maximum(spath, 0), max(width - 1, 0))
        sel = (spath >= 0) & (spath < width) & member[pid][safe]
        if corpus.ov_member is not None:
            # ephemeral batches carry overlay path entries (novel label/
            # annotation keys) whose membership lives in the batch blocks
            loc = spath - corpus.v_base
            b = corpus.ov_member.shape[0]
            safe_loc = np.clip(loc, 0, max(b - 1, 0))
            ov = (loc >= 0) & (loc < b) & corpus.ov_member[safe_loc, pid]
            sel = np.where(loc >= 0, ov, sel)
        return sel, vids

    def _pattern_value_counts(self, corpus: _Corpus, pid: int):
        """-> ([V] int distinct-row counts per value id at tokens
        matching pattern `pid`, any_fallback_rows). Cached on the corpus
        (the ephemeral webhook path reuses the persistent inventory's
        counts across requests)."""
        if corpus.value_counts is None:
            corpus.value_counts = {}
        cached = corpus.value_counts.get(pid)
        if cached is not None:
            return cached
        sel, vids = self._pattern_tokens(corpus, pid)
        valid = sel & (vids >= 0)
        rows, cols = np.nonzero(valid)
        tv = vids[rows, cols]
        if tv.size == 0:
            counts = np.zeros((len(self.vocab),), np.int64)
        else:
            pairs = np.unique(rows.astype(np.int64) * (tv.max() + 1) + tv)
            uniq_vids = pairs % (tv.max() + 1)
            counts = np.bincount(uniq_vids, minlength=len(self.vocab))
        result = (counts, bool(np.asarray(corpus.row_fallback).any()))
        corpus.value_counts[pid] = result
        return result

    # -- external data (docs/externaldata.md) --------------------------------

    def _extdata_row_bits(
        self, target: str, corpus: _Corpus, name: str
    ) -> np.ndarray:
        """Per-row screen bits for an "extdata:<kind>:<mode>" feature.

        Key extraction (analyzer-recorded input-derived keys
        expressions, evaluated per review) is cached on the corpus; the
        batch's deduped union feeds ONE system.prefetch per dispatch —
        that call IS the one-outbound-fetch-per-(provider, batch)
        contract for the fused path. Bits:
          * mode "err" (provably error-gated templates): True iff some
            key of the row is NOT a clean cache hit — clean rows can
            never produce an error entry, so they stay fused;
          * mode "all": all-ones (the feature exists to drive
            prefetch; violations may depend on response values, so
            every matching row re-checks).
        """
        n = len(corpus.reviews)
        ones = np.ones(n, bool)
        # warm_review_path seeds coarse all-ones bits: the warmup batch
        # only needs the right SHAPES, and its synthetic reviews must
        # never leak warmup keys into a real provider fetch
        if corpus.row_feats and name in corpus.row_feats:
            return corpus.row_feats[name]
        system = self.external_data
        parts = name.split(":")
        kind = parts[1] if len(parts) > 1 else ""
        mode = parts[2] if len(parts) > 2 else "all"
        if system is None or not kind:
            return ones
        report = self.template_report(target, kind)
        calls = getattr(report, "external_calls", None) if report else None
        if not calls:
            return ones
        if corpus.ext_keys is None:
            corpus.ext_keys = {}
        per_row = corpus.ext_keys.get(name)
        if per_row is None:
            from ..externaldata.extract import extract_keys

            per_row = []
            for review in corpus.reviews:
                wants: Optional[Dict[str, set]] = {}
                for call in calls:
                    if not call.extractable or not call.provider:
                        wants = None
                        break
                    keys = extract_keys(self.interp, call, review)
                    if keys is None:
                        wants = None
                        break
                    wants.setdefault(call.provider, set()).update(keys)
                per_row.append(wants)
            corpus.ext_keys[name] = per_row
        union: Dict[str, set] = {}
        for wants in per_row:
            if wants:
                for p, ks in wants.items():
                    union.setdefault(p, set()).update(ks)
        if union:
            system.prefetch(union)
        if mode != "err":
            return ones
        bits = np.zeros(n, bool)
        for i, wants in enumerate(per_row):
            if wants is None:
                bits[i] = True  # unextractable row: route it (sound)
                continue
            for p, ks in wants.items():
                if any(not system.probe_clean(p, k) for k in ks):
                    bits[i] = True
                    break
        bits |= np.asarray(corpus.row_fallback, bool)
        return bits

    def _prefetch_external(self, target: str, reviews: Sequence[Any]):
        """Batch-plane prefetch for every external-data template in the
        constraint set: extract + dedupe the batch's keys, then at most
        one outbound fetch per provider. Best-effort — resolution
        answers failures per the provider's failurePolicy."""
        system = self.external_data
        if system is None:
            return
        try:
            from ..externaldata.extract import batch_wants

            wants_total: Dict[str, set] = {}
            with self._mutex:
                for (t, kind) in list(self._kind_modules):
                    if t != target:
                        continue
                    rep = self.template_report(t, kind)
                    calls = getattr(rep, "external_calls", None)
                    if not calls:
                        continue
                    w = batch_wants(self.interp, calls, reviews)
                    if w:
                        for p, ks in w.items():
                            wants_total.setdefault(p, set()).update(ks)
            if wants_total:
                # OUTSIDE the serving mutex: a slow provider must stall
                # only this batch, never the whole admission plane
                system.prefetch(wants_total)
        except Exception:
            pass

    def _redispatch_chunk(self, policy, corpus: _Corpus, stacked, ci: int,
                          n_hot: int, require_compiled: bool = False):
        """Overflow path: one chunk had more violating rows than the
        compaction window — rerun just that chunk with room. The row
        feature planes ride along (ADVICE r3: dropping them widens the
        screen, so the rerun could flag more hot rows than the refined
        n_hot the cap was sized from); the cap still doubles until the
        rerun's own n_hot fits, so no hot row is ever truncated."""
        from ..parallel.sharding import StagedBatch

        self._hot_redispatches += 1
        self._count("driver_hot_redispatch_total")
        r_cap = 1 << (n_hot - 1).bit_length()
        batch = StagedBatch(
            fb_dev={k: v[ci] for k, v in stacked.fb_dev.items()},
            tok_dev={k: v[ci] for k, v in stacked.tok_dev.items()},
            row_fb=stacked.row_fb[ci],
            n_valid=stacked.n_valids[ci],
            key=("chunkview", stacked.key, stacked.chunk),
        )
        row_in = {
            k: v[ci] for k, v in (stacked.row_dev or {}).items()
        }
        while True:
            out = self.kernel.dispatch_need(
                policy, batch, (corpus.g, corpus.g1), r_cap=r_cap, row_in=row_in,
                ov_in=stacked.ov_dev, v_base=stacked.v_base,
                require_compiled=require_compiled,
            )
            if out[2] <= min(r_cap, stacked.chunk):
                return out
            r_cap = min(2 * r_cap, stacked.chunk)

    def _need_pairs_np(self, target, cs, corpus, ns_cache, n):
        """Numpy path (use_jax=False): same pair semantics, eager host
        eval — used by tests that pin device/host equivalence."""
        fire("driver.device_dispatch")
        compiled = [p for p in cs.programs if p is not None]
        handler = self._handler(target)
        match = np.zeros((len(cs.constraints), n), bool)
        for i, c in enumerate(cs.constraints):
            for j, r in enumerate(corpus.reviews):
                match[i, j] = handler.matches_constraint(c, r, ns_cache)
        prog_rows_arr = np.asarray(cs.prog_rows, np.int64)
        compiled_c = prog_rows_arr >= 0
        row_fb = np.asarray(corpus.row_fallback[:n], bool)
        viol = np.zeros((len(cs.constraints), n), bool)
        if compiled:
            overlay = _corpus_overlay(corpus)
            needed = sorted(
                {f for p in compiled for f in p.row_features}
            )
            row = (
                self._row_feature_bits(target, corpus, needed)
                if needed
                else None
            )
            counts = np.stack(
                [self.evaluator.eval_np(
                    p, corpus.tok, g=(corpus.g, corpus.g1), overlay=overlay,
                    row=row)
                 for p in compiled],
                axis=0,
            )
            viol[compiled_c] = counts[prog_rows_arr[compiled_c]] > 0
        fallback_pair = ~compiled_c[:, None] | row_fb[None, :]
        need = match & (viol | fallback_pair)
        pairs = [(int(a), int(b)) for a, b in np.argwhere(need.T)]
        stat_c = int((match & ~fallback_pair).sum())
        stat_i = int((match & fallback_pair).sum())
        return pairs, stat_c, stat_i

    # -- hook overrides ------------------------------------------------------

    def _violation(
        self, target: str, input: Dict[str, Any], trace: Optional[List[str]]
    ) -> List[Result]:
        review = H.hook_get_default(input, "review", {})
        handler = self._handler(target)
        constraints = self._constraints(target)
        if not constraints:
            return []
        ns_cache = self._ns_cache(target)
        results: List[Result] = []
        if handler.review_autorejects(review, ns_cache):
            for constraint in constraints:
                if handler.constraint_needs_context(constraint):
                    results.append(_autoreject_result(constraint, review))
                    if trace is not None:
                        trace.append(f"autoreject: {_cname(constraint)}")
        results.extend(
            self._eval_reviews(target, [review], trace, corpus=None)
        )
        return results

    def query_many(
        self, path: str, inputs: Sequence[Any], tracing: bool = False
    ) -> List[Response]:
        """Batched violation hook: every review in `inputs` evaluates in
        one fused device dispatch (the webhook micro-batch path). Other
        hooks and tracing queries fall back to the serial default."""
        from .driver import _HOOK_RE

        m = _HOOK_RE.match(path)
        if (
            m is None
            or m.group(2) != "violation"
            or tracing
            or not self.use_jax
        ):
            return super().query_many(path, inputs, tracing)
        target = m.group(1)
        if self.external_data is not None:
            # batch plane: open a fresh fetch epoch and prefetch the
            # batch's deduped keys (one outbound fetch per provider)
            # BEFORE routing — both the fused screen and the
            # interpreter rung then serve from the response cache
            self.external_data.begin_batch()
            self._prefetch_external(
                target,
                [H.hook_get_default(i or {}, "review", {}) for i in inputs],
            )
        cold = len(inputs) >= MIN_DEVICE_BATCH and not self.review_path_warm(
            target
        )
        if cold:
            # serve-while-compiling: don't block this batch on a jit
            # compile (tens of seconds cold) — serve it on the
            # interpreter and compile in the background; once warm the
            # route swaps to the fused path
            self.cold_batches += 1
            self._count("driver_cold_batches_total")
            self._kick_warm(target, inputs)
        if cold or len(inputs) < MIN_DEVICE_BATCH:
            # adaptive routing: a tiny batch finishes faster on the
            # serial interpreter than a device round trip would take
            # (results are bit-identical by the driver-parity contract)
            with self._mutex:
                return [
                    Response(
                        target=target,
                        results=RegoDriver._violation(
                            self, target, i or {}, None
                        ),
                    )
                    for i in inputs
                ]
        return self._query_many_device(target, inputs)

    def query_host(
        self, path: str, input: Any = None, subset=None
    ) -> Response:
        """The host-oracle rung of the degradation ladder: evaluate on
        the INTERPRETER, never touching the device — the path the
        webhook's circuit breaker degrades to when the fused dispatch
        is failing (a faulted device must not be paid a second doomed
        attempt per request). `subset` scopes the evaluation to one
        partition's constraints (docs/robustness.md §Fault domains), so
        a single sick device degrades ONLY its constraint subset to the
        interpreter while every other partition stays fused. Results
        are bit-identical to the fused path by the driver-parity
        contract."""
        m = _HOOK_RE.match(path)
        if m is None:
            raise ValueError(f"unsupported query path: {path!r}")
        target, hook = m.group(1), m.group(2)
        with self._mutex:
            if hook == "violation":
                constraints = None
                if subset is not None:
                    sub = frozenset(subset)
                    constraints = [
                        c for c in self._constraints(target)
                        if constraint_key(c) in sub
                    ]
                results = RegoDriver._violation(
                    self, target, input or {}, None,
                    constraints=constraints,
                )
            else:
                results = RegoDriver._audit(self, target, None)
        return Response(target=target, results=results)

    # -- partitioned dispatch (docs/robustness.md §Fault domains) ------------

    def query_many_subset(
        self, path: str, inputs: Sequence[Any], subset, device: int = 0,
        partition=None,
    ) -> List[Response]:
        """Partition-scoped fused dispatch: evaluate ONLY `subset`'s
        constraints for every input, as one device execution attributed
        to logical `device`. The device-labeled fault point
        (`driver.device_dispatch[device=N]`) gates the whole partition
        dispatch, so the chaos suite can sicken exactly one fault
        domain. Small batches keep the adaptive interpreter route (same
        policy as `query_many`; results identical by the parity
        contract). Merged across a plan's partitions, results are
        bit-identical to the monolithic dispatch
        (`parallel.partition.merge_partition_results` + the partition
        parity battery)."""
        m = _HOOK_RE.match(path)
        if m is None or m.group(2) != "violation":
            raise ValueError(f"unsupported partition query path: {path!r}")
        target = m.group(1)
        fire(device_point("driver.device_dispatch", device))
        with self._mutex:
            cs = self._subset_cset(target, frozenset(subset))
            if cs is None:
                return [
                    Response(target=target, results=[]) for _ in inputs
                ]
            if (
                self.use_jax
                and len(inputs) < MIN_DEVICE_BATCH
                and len(inputs) * len(cs.constraints) < MIN_DEVICE_PAIRS
            ):
                # adaptive routing, pair-aware (MIN_DEVICE_PAIRS): a
                # tiny SPARSE batch finishes faster on the serial
                # interpreter than a device round trip would take, but
                # a mask-sliced sub-batch is dense — few reviews, many
                # matching constraints — and belongs on the device
                return [
                    Response(
                        target=target,
                        results=RegoDriver._violation(
                            self, target, i or {}, None,
                            constraints=cs.constraints,
                        ),
                    )
                    for i in inputs
                ]
            handler = self._handler(target)
            ns_cache = self._ns_cache(target)
            reviews = [
                H.hook_get_default(i or {}, "review", {}) for i in inputs
            ]
            rej_constraints = [
                c for c in cs.constraints
                if handler.constraint_needs_context(c)
            ]
            autorejects: List[List[Result]] = []
            for review in reviews:
                out: List[Result] = []
                if rej_constraints and handler.review_autorejects(
                    review, ns_cache
                ):
                    out = [
                        _autoreject_result(c, review)
                        for c in rej_constraints
                    ]
                autorejects.append(out)
            # verdict-integrity canaries ride in the padding slots this
            # bucket already wastes; their results are stripped below,
            # BEFORE any merge — a canary verdict is never a policy
            # outcome (docs/robustness.md §Verdict integrity)
            n_live = len(reviews)
            sigkey = cs.signature or f"gen{self._constraint_gen}"
            reviews, canary_autorej = self._canary_pack(
                target, sigkey, cs.constraints, reviews,
                handler, ns_cache, rej_constraints,
            )
            split = self._eval_reviews_split(
                target, reviews, None, None, cset=cs,
                partition=(partition if partition is not None else device),
            )
            canary_split = split[n_live:]
            split = split[:n_live]
        self._canary_check(
            target, sigkey, device, canary_split, canary_autorej,
            subset=frozenset(subset),
        )
        return [
            Response(target=target, results=auto + ev)
            for auto, ev in zip(autorejects, split)
        ]

    def prepare_subset(self, path: str, subset, device: int = 0) -> bool:
        """Stage one partition's sub-program onto its device: build the
        subset constraint set and upload its policy tensors. This is
        the restage step of quarantine re-homing — the device-labeled
        fault point (`driver.restage[device=N]`) makes restage failure
        injectable, and the quarantine manager retries with backoff
        while the subset serves from the host rung.

        Incremental compile plane (docs/compile.md): a signature-
        unchanged subset carries its staged policy across the
        generation bump (no restage). A changed subset builds a SHADOW
        constraint set, stages its policy OFF the serving mutex — in-
        flight batches keep dispatching the old sub-program meanwhile —
        then atomically swaps it live. The `compile.swap` fault point
        sits between stage and swap: an injected mid-swap failure
        leaves the old sub-program serving. Returns False (not an
        error) when the corpus churned again mid-stage — the caller's
        next restage pass picks up the newer generation."""
        m = _HOOK_RE.match(path)
        if m is None or m.group(2) != "violation":
            raise ValueError(f"unsupported partition query path: {path!r}")
        target = m.group(1)
        fs = frozenset(subset)
        fire(device_point("driver.restage", device))
        with self._mutex:
            gen = self._constraint_gen
            cs = self._cset_sub.get((target, fs))
            if cs is not None and cs.constraint_gen != gen:
                sig = self._subset_signature(target, fs)
                if cs.signature is not None and cs.signature == sig:
                    cs.constraint_gen = gen
                    self.subset_carryforwards += 1
                    self._count("program_carryforward_total", target=target)
                else:
                    cs = None
            if cs is not None:
                if (
                    self.use_jax
                    and self.kernel is not None
                    and cs.policy is None
                ):
                    cs.policy = self.kernel.stage_policy(cs.programs, cs.ms)
                return True
            shadow = self._build_subset_cset(target, fs)
            if shadow is None:
                self._cset_sub.pop((target, fs), None)
                return True
        # OFF the mutex: the policy upload / XLA compile — the live
        # entry (if any) keeps serving fused dispatches throughout
        if self.use_jax and self.kernel is not None:
            shadow.policy = self.kernel.stage_policy(
                shadow.programs, shadow.ms
            )
        # golden swap gate (docs/robustness.md §Verdict integrity): the
        # staged shadow sub-program must reproduce the golden canary
        # digests before it may swap live — a corrupting compile or a
        # bad staged tensor is rejected here, with the OLD sub-program
        # still serving
        if self.integrity is not None and shadow.constraints:
            if not self._golden_gate(target, shadow):
                self._count(
                    "program_swap_rejected_total",
                    reason="golden_mismatch", target=target,
                )
                return False
        # mid-swap fault point: failure here must leave the old
        # sub-program live (tests/test_compile_plane.py)
        fire("compile.swap")
        with self._mutex:
            if self._constraint_gen != gen:
                return False
            while len(self._cset_sub) >= self._cset_sub_max:
                self._cset_sub.pop(next(iter(self._cset_sub)), None)
            self._cset_sub[(target, fs)] = shadow
            self._swap_gen += 1
            self.subset_swaps += 1
            self._count("program_swap_total", target=target)
        if self.program_store is not None:
            try:
                self.program_store.attest()
            except Exception:
                pass
        return True

    def _golden_gate(self, target: str, shadow) -> bool:
        """Warm-swap integrity gate: replay the golden canary batch
        through the STAGED (not yet live) sub-program and digest-compare
        against the interpreter-pinned golden set. The
        `integrity.selftest` fault point injects a corrupting shadow
        slot. A shape bucket with no compiled kernel yet skips the
        fused replay (ColdKernel) — the canary tier catches corruption
        on the first live dispatch instead."""
        from ..integrity.canary import result_digest
        from ..parallel.sharding import ColdKernel

        integ = self.integrity
        sigkey = shadow.signature or f"gen{self._constraint_gen}"
        try:
            entry = integ.golden_for(
                target, sigkey, shadow.constraints,
                self._interp_closure(target, shadow.constraints),
            )
        except Exception:
            entry = None
        if entry is None or not entry.get("reviews"):
            return True
        try:
            fire("integrity.selftest")
        except FaultError:
            return False
        with self._mutex:
            handler = self._handler(target)
            ns_cache = self._ns_cache(target)
        rej_constraints = [
            c for c in shadow.constraints
            if handler.constraint_needs_context(c)
        ]
        try:
            split = self._eval_reviews_split(
                target, list(entry["reviews"]), None, None,
                require_compiled=True, cset=shadow,
            )
        except ColdKernel:
            return True
        except Exception:
            return False
        got = []
        for review, ev in zip(entry["reviews"], split):
            auto: List[Result] = []
            if rej_constraints and handler.review_autorejects(
                review, ns_cache
            ):
                auto = [
                    _autoreject_result(c, review) for c in rej_constraints
                ]
            got.append(result_digest(auto + ev))
        return got == entry["digests"][: len(got)]

    # -- serve-while-compiling (cold-start) ----------------------------------

    def review_path_warm(self, target: str) -> bool:
        """True when the fused review dispatch is compiled for the
        CURRENT constraint generation (numpy mode has no compile)."""
        if not self.use_jax:
            return True
        return self._review_warm.get(target) == self._constraint_gen

    def _kick_warm(self, target: str, inputs: Sequence[Any]) -> None:
        """Start (at most one per target) a background compile of the
        fused review path, shaped by the live batch that found it cold."""
        with self._warm_lock:
            if target in self._warming:
                return
            self._warming.add(target)
        reviews = [
            H.hook_get_default(i or {}, "review", {}) for i in inputs
        ]

        def run():
            try:
                self.warm_review_path(target, reviews)
            except Exception:
                pass  # best-effort; the next cold batch re-kicks
            finally:
                with self._warm_lock:
                    self._warming.discard(target)

        # NON-daemon: the interpreter joins it at exit. A daemon thread
        # killed mid-XLA-compile during teardown aborts the whole
        # process (SIGABRT, 'FATAL: exception not rethrown') — a passing
        # test run or a finished bench child would die rc=134.
        _threading.Thread(
            target=run, name=f"gk-warm-{target}", daemon=False
        ).start()

    def warm_review_path(
        self, target: str, reviews: Sequence[Any]
    ) -> bool:
        """Compile the fused review dispatch for `reviews`' batch/shape
        buckets WITHOUT holding the serving mutex during the compile.

        Phase 1 (under the mutex, fast): snapshot the compiled programs
        into a throwaway _ConstraintSet and encode the ephemeral corpus
        with COARSE (all-ones) row-feature bits — the jit is shaped by
        the presence of feature planes, not their values, so the warm
        never stalls the mutex on a full audit-corpus encode (ADVICE
        r5 review). Phase 2 (lock-free): run the device dispatch — XLA
        compilation happens here while the interpreter keeps serving.
        Phase 3 (under the mutex): mark the route warm iff the
        constraint generation is unchanged — the atomic swap. Phase 4
        (under the mutex, best-effort): precompute the audit corpus +
        true feature bits so the first REAL device batch doesn't pay
        that one-time encode inline."""
        if not self.use_jax:
            return True
        reviews = list(reviews)
        if not reviews:
            return False
        with self._mutex:
            gen = self._constraint_gen
            cs_live = self._constraint_set(target)
            if cs_live is None:
                # nothing to compile: an empty policy set serves warm
                self._review_warm[target] = gen
                return True
            ns_cache = self._ns_cache(target)
            cs = _ConstraintSet(
                constraint_gen=cs_live.constraint_gen,
                constraints=cs_live.constraints,
                ms=cs_live.ms,
                programs=cs_live.programs,
                prog_rows=cs_live.prog_rows,
                # reuse the staged policy when present (read-only device
                # state, content-keyed): re-staging per warm re-uploads
                # ms_dev/stacked_consts into a throwaway for nothing
                policy=cs_live.policy,
            )
            corpus = self._ephemeral_corpus(
                target, cs, reviews, ns_cache, coarse_feats=True
            )
            self.patterns.sync()
            self.tables.sync()
            needed = sorted(
                {
                    f
                    for p in cs.programs
                    if p is not None
                    for f in p.row_features
                }
            )
            if needed:
                # coarse bits: route everything (sound); shapes match
                # the real dispatch so the compile is reusable
                ones = np.ones(len(corpus.reviews), bool)
                corpus.row_feats = {name: ones for name in needed}
        try:
            # named fault point: "hang" simulates an XLA compile stall
            # (tens of seconds is realistic), "error" a compile failure
            # — the serving route must stay on the interpreter either way
            fire("driver.compile")
            self._need_pairs(target, cs, corpus)
        except Exception:
            return False
        warmed = False
        with self._mutex:
            if self._constraint_gen == gen:
                self._review_warm[target] = gen
                warmed = True
        # extdata bits are volatile (they track the live response
        # cache) and extraction on warmup reviews would leak synthetic
        # warmup keys into a real provider fetch — only the
        # corpus-constant invdup bits are worth precomputing here
        precompute = [n for n in needed if not n.startswith("extdata:")]
        if warmed and precompute:
            # pay the one-time audit-corpus encode + true feature bits
            # HERE (background thread) rather than inline in the first
            # real device batch; admission briefly queues behind this
            # acquisition, which is the pre-existing per-data-generation
            # cost — not the per-boot compile this method removes
            try:
                with self._mutex:
                    real = self._ephemeral_corpus(
                        target, cs, reviews[:1], self._ns_cache(target)
                    )
                    self._row_feature_bits(target, real, precompute)
            except Exception:
                pass
        # content-address + attest whatever the compile just landed in
        # the XLA cache dir, so identical machines can adopt it
        if warmed and self.program_store is not None:
            try:
                self.program_store.attest()
            except Exception:
                pass
        return warmed

    def _query_many_device(
        self, target: str, inputs: Sequence[Any]
    ) -> List[Response]:
        with self._mutex:
            handler = self._handler(target)
            constraints = self._constraints(target)
            ns_cache = self._ns_cache(target)
            reviews = [
                H.hook_get_default(i or {}, "review", {}) for i in inputs
            ]
            # autoreject factors (match.needs_ns_selector docstring):
            # the constraint half is per CONSTRAINT, the cache-miss half
            # per REVIEW — O(R + C), not the O(R x C) loop the predicate
            # naively implies (VERDICT r2 weak #9)
            rej_constraints = [
                c for c in constraints
                if handler.constraint_needs_context(c)
            ]
            autorejects: List[List[Result]] = []
            for review in reviews:
                out: List[Result] = []
                if rej_constraints and handler.review_autorejects(
                    review, ns_cache
                ):
                    out = [
                        _autoreject_result(constraint, review)
                        for constraint in rej_constraints
                    ]
                autorejects.append(out)
            from ..parallel.sharding import ColdKernel

            # monolithic dispatch canaries: same padding-slot packing
            # as the partitioned path, golden keyed per constraint
            # generation (the monolith has no subset signature), all
            # attributed to logical device 0
            n_live = len(reviews)
            sigkey = f"gen{self._constraint_gen}"
            reviews, canary_autorej = self._canary_pack(
                target, sigkey, constraints, reviews,
                handler, ns_cache, rej_constraints,
            )
            try:
                split = self._eval_reviews_split(
                    target, reviews, None, None, require_compiled=True
                )
                canary_split = split[n_live:]
                split = split[:n_live]
            except ColdKernel:
                # novel shape bucket before its kernel compiled: serve
                # this batch on the interpreter and compile it in the
                # background (holding every admission on an inline XLA
                # compile would blow the webhook deadline)
                self.cold_batches += 1
                self._count("driver_cold_batches_total")
                self._kick_warm(target, inputs)
                split = [
                    RegoDriver._violation(self, target, i or {}, None)
                    for i in inputs
                ]
                # interp route already emits autoreject results
                return [
                    Response(target=target, results=r) for r in split
                ]
        self._canary_check(target, sigkey, 0, canary_split, canary_autorej)
        return [
            Response(target=target, results=auto + ev)
            for auto, ev in zip(autorejects, split)
        ]

    def _audit(self, target: str, trace: Optional[List[str]]) -> List[Result]:
        with self._mutex:
            corpus = self._audit_corpus(target)
        if corpus is None:
            self.stats = {}
            return []
        if self.external_data is not None:
            # each sweep is one batch epoch: the corpus's deduped keys
            # fetch once; flagged rows then render from the cache
            self.external_data.begin_batch()
            self._prefetch_external(target, corpus.reviews)
        return self._eval_reviews(
            target, corpus.reviews, trace, corpus=corpus
        )

    def _eval_reviews(
        self,
        target: str,
        reviews: List[Any],
        trace: Optional[List[str]],
        corpus: Optional[_Corpus],
    ) -> List[Result]:
        split = self._eval_reviews_split(target, reviews, trace, corpus)
        return [r for sub in split for r in sub]

    def _eval_reviews_split(
        self,
        target: str,
        reviews: List[Any],
        trace: Optional[List[str]],
        corpus: Optional[_Corpus],
        require_compiled: bool = False,
        cset: Optional[_ConstraintSet] = None,
        partition=None,
    ) -> List[List[Result]]:
        """Shared compiled-path evaluation: match x programs on device,
        interpreter rendering of the sparse violating pairs; results
        grouped per review (review-major order preserved).
        require_compiled propagates to the kernel dispatch: ColdKernel
        escapes (before any result is produced) when this batch's shape
        bucket has no compiled entry yet. `cset` overrides the target's
        full constraint set with a partition-scoped one
        (query_many_subset); `partition` labels the cost-attribution
        rows this dispatch's device time lands in."""
        import time as _time

        t_start = _time.perf_counter()
        with self._mutex:
            cs = cset if cset is not None else self._constraint_set(target)
            if cs is None:
                self.stats = {}
                return [[] for _ in reviews]
            ns_cache = self._ns_cache(target)
            inventory = self._inventory(target)
            if corpus is None:
                corpus = self._ephemeral_corpus(
                    target, cs, reviews, ns_cache
                )
            self.patterns.sync()
            self.tables.sync()
            t_encoded = _time.perf_counter()
            c_count = len(cs.constraints)
            n_count = len(reviews)
            if self.use_jax:
                pairs, stat_c, stat_i = self._need_pairs(
                    target, cs, corpus, require_compiled=require_compiled
                )
            else:
                pairs, stat_c, stat_i = self._need_pairs_np(
                    target, cs, corpus, ns_cache, n_count
                )
            t_dispatched = _time.perf_counter()
            # only the sparse pair set needing interpreter work is
            # visited in Python — violating compiled pairs (count > 0)
            # plus every matched fallback pair, review-major (matching
            # RegoDriver._audit's emit order)
            render_cache: Optional[Dict[Tuple[int, int], List[Result]]]
            render_cache = None
            if corpus.data_gen >= 0 and trace is None:
                gens = (self._data_gen, self._constraint_gen)
                cached = self._render_cache.get(target)
                if cached is None or cached[0] != gens:
                    cached = (gens, {})
                    self._render_cache[target] = cached
                render_cache = cached[1]
            # compiled-render pre-pass (VERDICT r3 #1): exact programs'
            # violating pairs render from their branch plans via one
            # numpy evaluation over the violating rows — no interpreter.
            # Pairs the plans cannot prove exact fall through below.
            # Traced requests keep the interpreter route so their traces
            # carry the per-pair evaluation lines.
            host_rendered: Dict[Tuple[int, int], List[Result]] = {}
            if trace is None:
                uncached = [
                    p
                    for p in pairs
                    if render_cache is None or p not in render_cache
                ]
                host_rendered = self._host_render_pairs(
                    cs, corpus, uncached, reviews
                )
            per_review: List[List[Result]] = [[] for _ in reviews]
            n_results = 0
            n_host = 0
            n_interp_render = 0
            n_pruned = 0
            n_cache_hits = 0
            frozen: Dict[int, Any] = {}  # review idx -> frozen review
            for n_i, c_i in pairs:
                out = None
                if render_cache is not None:
                    out = render_cache.get((n_i, c_i))
                    if out is not None:
                        n_cache_hits += 1
                if out is None:
                    out = host_rendered.get((n_i, c_i))
                    if out is not None:
                        n_host += 1
                    else:
                        fr = frozen.get(n_i)
                        if fr is None:
                            fr = frozen[n_i] = freeze(reviews[n_i])
                        prog = cs.programs[c_i]
                        prune = prog.prune if prog is not None else None
                        if prune is not None:
                            out = self._render_pruned(
                                target, cs.constraints[c_i],
                                reviews[n_i], prune, trace, fr
                            )
                            n_pruned += 1
                        else:
                            out = self._eval_template(
                                target, cs.constraints[c_i], reviews[n_i],
                                inventory, trace, frozen_review=fr
                            )
                        n_interp_render += 1
                    if render_cache is not None:
                        self._render_cache_put(
                            render_cache, (n_i, c_i), out
                        )
                per_review[n_i].extend(out)
                n_results += len(out)
            t_done = _time.perf_counter()
            # the per-query cost-center split: how long this evaluation
            # spent flattening/encoding reviews into tensors, executing
            # the fused device dispatch (incl. any inline jit compile),
            # and rendering violation messages. The micro-batch bridge
            # and audit manager turn these into trace spans.
            phase_seconds = {
                "flatten_encode": t_encoded - t_start,
                "device_dispatch": t_dispatched - t_encoded,
                "render": t_done - t_dispatched,
            }
            # per-constraint device-time accounting: the measured
            # device-execute window, apportioned by static cost over
            # the constraint set this dispatch evaluated
            self._attribute_dispatch(
                cs, phase_seconds["device_dispatch"], partition
            )
            self.stats = {
                "compiled_pairs": stat_c,
                "interp_pairs": stat_i,
                "n_reviews": n_count,
                "n_constraints": c_count,
                "n_results": n_results,
                "host_rendered_pairs": n_host,
                "interp_rendered_pairs": n_interp_render,
                "pruned_renders": n_pruned,
                # violating pairs answered straight from the render
                # cache (the decision log's per-request cache fact)
                "render_cache_hits": n_cache_hits,
                "render_errors": self._render_errors,
                "render_cache_evictions": self._render_cache_evictions,
                "hot_redispatches": self._hot_redispatches,
                # dead token slots the IR liveness mask dropped from
                # THIS batch's encode (0 for the keep-all audit corpus)
                "columns_skipped_static": int(
                    getattr(corpus, "skipped_static", 0)
                ),
                "phase_seconds": phase_seconds,
                # machine-readable WHY for every wholesale-interpreter
                # template in this query's constraint set
                "fallback_codes": dict(cs.fallback_codes or {}),
                "analyzer_mismatches": self.analyzer_mismatches,
            }
            if self.metrics is not None:
                path = "audit" if corpus.data_gen >= 0 else "webhook"
                m = self.metrics
                m.record("driver_pairs_total", stat_c, route="compiled",
                         path=path)
                m.record("driver_pairs_total", stat_i, route="interp",
                         path=path)
                m.record("driver_render_total", n_host, route="host")
                m.record("driver_render_total",
                         n_interp_render - n_pruned, route="interp")
                m.record("driver_render_total", n_pruned, route="pruned")
                for phase, dt in phase_seconds.items():
                    m.observe("driver_phase_seconds", dt, phase=phase,
                              path=path)
            if trace is not None:
                trace.append(
                    f"tpu dispatch: {self.stats['compiled_pairs']} compiled "
                    f"pairs, {self.stats['interp_pairs']} interpreter pairs"
                )
            return per_review

    # -- derived-key prune rendering -----------------------------------------

    def _prune_oracle(self, target: str, kind: str, params: Any):
        key = (target, kind, _params_key(params))
        cached = self._prune_oracles.get(key)
        if cached is None:
            cached = self._make_oracle(target, kind, params)
            self._prune_oracles[key] = cached
        return cached

    @staticmethod
    def _collect_path_values(node: Any, segs: Tuple[str, ...]) -> List[Any]:
        """All SCALAR values reachable from `node` along a path whose
        wildcard segments ("#" array level, "*" object key, "?" either)
        iterate every child. Used host-side for both sides of a
        path-form prune plan: inventory objects (index keys) and the
        review object (lookup keys). Collecting a superset is sound —
        the interpreter re-checks candidates — so "#"/"?" iterate both
        dicts and lists rather than discriminating."""
        out: List[Any] = []
        frontier = [node]
        for seg in segs:
            nxt: List[Any] = []
            wild = seg in ("#", "*", "?")
            for n in frontier:
                if wild:
                    if isinstance(n, dict):
                        nxt.extend(n.values())
                    elif isinstance(n, list):
                        nxt.extend(n)
                elif isinstance(n, dict):
                    key = unesc_seg(seg)
                    if key in n:
                        nxt.append(n[key])
            frontier = nxt
            if not frontier:
                break
        for v in frontier:
            if isinstance(v, (str, int, float, bool)) or v is None:
                out.append(v)
        return out

    @staticmethod
    def _plan_key(plan: Dict[str, Any]) -> Tuple:
        if "fn" in plan:
            return ("fn", plan["fn"], plan["tree"])
        return ("path", plan["path"], plan["review_pattern"], plan["tree"])

    def _prune_index(
        self, target: str, kind: str, params: Any, plan: Dict[str, Any]
    ):
        """{frozen key -> [(path segs, obj)]} over the inventory tree —
        built once per data generation. fn-form plans evaluate the
        join's pure Rego helper host-side (flatten_selector,
        /root/reference/library/general/uniqueserviceselector/src.rego);
        path-form plans collect the values at the join's relative path
        (spec.rules[_].host,
        /root/reference/library/general/uniqueingresshost/src.rego) —
        one object indexes under EACH of its keys."""
        ikey = (target, kind, _params_key(params)) + self._plan_key(plan)
        cached = self._prune_indexes.get(ikey)
        if cached is not None and cached[0] == self._data_gen:
            return cached[1]
        fn = plan.get("fn")
        oracle = (
            self._prune_oracle(target, kind, params)
            if fn is not None
            else None
        )
        depth = 4 if plan["tree"] == "namespace" else 3
        tree = self.storage.get(["external", target, plan["tree"]], {})
        index: Dict[Any, List[Tuple[Tuple[str, ...], Any]]] = {}

        def rec(node, segs):
            if len(segs) == depth:
                if oracle is not None:
                    k, defined = oracle(fn, node)
                    if defined:
                        index.setdefault(freeze(k), []).append((segs, node))
                else:
                    entry = (segs, node)
                    seen = set()
                    for k in self._collect_path_values(node, plan["path"]):
                        fk = freeze(k)
                        if fk not in seen:
                            seen.add(fk)
                            index.setdefault(fk, []).append(entry)
                return
            if isinstance(node, dict):
                for key2, child in node.items():
                    rec(child, segs + (key2,))

        if isinstance(tree, dict):
            rec(tree, ())
        self._prune_indexes[ikey] = (self._data_gen, index)
        return index

    def _render_pruned(
        self,
        target: str,
        constraint: Dict[str, Any],
        review: Any,
        plan: Dict[str, Any],
        trace: Optional[List[str]],
        frozen_review: Any,
    ) -> List[Result]:
        """Interpreter render against a PRUNED inventory: only the
        derived-key index's candidates for this review's key(s). Sound
        because the compile proved the violating clause implies the
        candidate and the review side share a key — F(candidate) ==
        F(review subdoc) for fn-form plans, path-values(candidate) ∩
        path-values(review) ≠ ∅ for path-form — and no other clause
        touches the inventory, so candidates are the only objects that
        can appear in any violation."""
        kind = constraint.get("kind")
        params = H.constraint_parameters(constraint)
        candidates: List[Tuple[Tuple[str, ...], Any]] = []
        if "fn" in plan:
            cur: Any = review
            for seg in plan["review_prefix"]:
                if not isinstance(cur, dict) or seg not in cur:
                    cur = None
                    break
                cur = cur[seg]
            if cur is not None:
                oracle = self._prune_oracle(target, kind, params)
                k, defined = oracle(plan["fn"], cur)
                if defined:
                    index = self._prune_index(target, kind, params, plan)
                    candidates = index.get(freeze(k), [])
        else:
            # path-form: candidates = union over the review's key values
            # (spec.rules[_].host yields one key per rule), deduped by
            # inventory path so a shared-host candidate appears once
            keys = {
                freeze(k)
                for k in self._collect_path_values(
                    review, plan["review_pattern"]
                )
            }
            if keys:
                index = self._prune_index(target, kind, params, plan)
                seen_segs = set()
                for fk in keys:
                    for segs, obj in index.get(fk, []):
                        if segs not in seen_segs:
                            seen_segs.add(segs)
                            candidates.append((segs, obj))
        pruned_tree: Dict[str, Any] = {}
        for segs, obj in candidates:
            node = pruned_tree
            for seg in segs[:-1]:
                node = node.setdefault(seg, {})
            node[segs[-1]] = obj
        pruned_inv = freeze({plan["tree"]: pruned_tree})
        return self._eval_template(
            target, constraint, review, pruned_inv, trace,
            frozen_review=frozen_review,
        )

    # -- compiled message rendering ------------------------------------------

    def _host_render_pairs(
        self,
        cs: _ConstraintSet,
        corpus: _Corpus,
        pairs: List[Tuple[int, int]],
        reviews: List[Any],
    ) -> Dict[Tuple[int, int], List[Result]]:
        """Render violating pairs of exact programs from their compiled
        branch plans (engine/render.py): one numpy branch evaluation per
        (program, violating-row-subset), then per-row message decoding
        from the token table + raw review — the interpreter never runs.
        Rows/pairs the plans cannot prove exact are omitted (the caller
        falls back per pair). Violation objects render once per
        (program, row) and fan out to every constraint sharing the
        program (identical params => identical violations; only the
        constraint/enforcement fields differ)."""
        out: Dict[Tuple[int, int], List[Result]] = {}
        by_prog: Dict[int, Tuple[Program, List[Tuple[int, int]]]] = {}
        for n_i, c_i in pairs:
            p = cs.programs[c_i]
            if p is None or not p.branches:
                continue
            if corpus.row_fallback[n_i]:
                continue  # overflow rows: interpreter territory
            ent = by_prog.get(id(p))
            if ent is None:
                ent = by_prog[id(p)] = (p, [])
            ent[1].append((n_i, c_i))
        if not by_prog:
            return out
        from ..engine.exprs import EvalCtx
        from ..engine.render import RenderSet

        member = np.asarray(self.patterns.member)
        capture = np.asarray(self.patterns.capture)
        tabs = {k: np.asarray(v) for k, v in self.tables.arrays().items()}
        overlay = _corpus_overlay(corpus)
        ov = overlay or {}
        corpus_vocab = corpus.vocab if corpus.vocab is not None else self.vocab
        for prog, plist in by_prog.values():
            rows = sorted({n for n, _ in plist})
            pos = {n: i for i, n in enumerate(rows)}
            idx = np.asarray(rows, np.int64)
            tok_slice = {k: v[idx] for k, v in corpus.tok.items()}
            ctx = EvalCtx(
                np=np,
                tok=tok_slice,
                pat_member=member,
                pat_capture=capture,
                str_tables=tabs,
                consts=prog.consts,
                g0=corpus.g,
                g1=corpus.g1,
                v_base=ov.get("v_base"),
                ov_member=ov.get("member"),
                ov_capture=ov.get("capture"),
                ov_tabs=ov.get("tabs"),
            )
            try:
                rset = RenderSet(prog, ctx, corpus_vocab)
                row_objs = {
                    n: rset.render_row(pos[n], reviews[n]) for n in rows
                }
            except Exception:
                # a plan evaluation bug must degrade to the interpreter,
                # never fail the sweep; surfaced via stats for tests
                self._render_errors += 1
                self._count("driver_render_errors_total")
                continue
            for n_i, c_i in plist:
                objs = row_objs.get(n_i)
                if objs is None:
                    continue
                out[(n_i, c_i)] = _results_from_objs(
                    objs, cs.constraints[c_i], reviews[n_i]
                )
        return out


def _corpus_overlay(corpus: _Corpus) -> Optional[Dict[str, Any]]:
    """Vocab-overlay ctx blocks for host/numpy evaluation paths."""
    if corpus.ov_member is None:
        return None
    return {
        "v_base": corpus.v_base,
        "member": corpus.ov_member,
        "capture": corpus.ov_capture,
        "tabs": corpus.ov_tabs,
    }


def _results_from_objs(
    objs: List[Any], constraint: Dict[str, Any], review: Any
) -> List[Result]:
    """Frozen violation objects -> Result list, mirroring the hook's
    shape exactly (RegoDriver._eval_template): msg-less violations drop,
    details default {} (client/regolib/src.go:23-42)."""
    from ..rego.values import thaw

    enforcement = H.enforcement_action(constraint)
    out: List[Result] = []
    for v in objs:
        tv = thaw(v)
        if not isinstance(tv, dict) or "msg" not in tv:
            continue
        out.append(
            Result(
                msg=tv["msg"],
                metadata={"details": H.hook_get_default(tv, "details", {})},
                constraint=constraint,
                review=review,
                enforcement_action=enforcement,
            )
        )
    return out


def _features_np(fb) -> Dict[str, np.ndarray]:
    """FeatureBatch -> plain numpy dict (same keys the kernel takes);
    chunks are sliced host-side and shipped per dispatch."""
    return {
        "group_id": np.asarray(fb.group_id),
        "kind_id": np.asarray(fb.kind_id),
        "kind_defined": np.asarray(fb.kind_defined),
        "is_ns": np.asarray(fb.is_ns),
        "has_namespace": np.asarray(fb.has_namespace),
        "ns_name_id": np.asarray(fb.ns_name_id),
        "obj_present": np.asarray(fb.obj_present),
        "old_present": np.asarray(fb.old_present),
        "obj_labels": np.asarray(fb.obj_labels),
        "old_labels": np.asarray(fb.old_labels),
        "nssel_defined": np.asarray(fb.nssel_defined),
        "nssel_labels": np.asarray(fb.nssel_labels),
        "nssel_empty": np.asarray(fb.nssel_empty),
    }


def _pad_rows(a: np.ndarray, pad: int, fill=None) -> np.ndarray:
    if pad <= 0:
        return a
    shape = (pad,) + a.shape[1:]
    if fill is None:
        if a.dtype == bool:
            fill_val = False
        else:
            fill_val = -1
    else:
        fill_val = fill
    padrows = np.full(shape, fill_val, a.dtype)
    return np.concatenate([a, padrows], axis=0)
