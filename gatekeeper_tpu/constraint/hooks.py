"""Target-neutral hook-library helpers.

The regolib hook semantics (client/regolib/src.go:7-85) that every
evaluation path shares — constraint spec access, enforcement action,
Rego-equality — factored out of the K8s matching oracle so engine code
(drivers, mutation, webhook) can consume them WITHOUT importing the
target-specific matching semantics in `constraint/match.py`. That
module is reached only through the `TargetHandler` interface
(docs/targets.md); this one is the neutral remainder.
"""

from __future__ import annotations

from typing import Any, Dict

_MISSING = object()


def get_default(obj: Any, field: str, default: Any) -> Any:
    """target-lib get_default (target_template_source.go:110-125).

    Null-valued fields count as missing.
    """
    if isinstance(obj, dict) and field in obj and obj[field] is not None:
        return obj[field]
    return default


def hook_get_default(obj: Any, field: str, default: Any) -> Any:
    """regolib hooks get_default (client/regolib/src.go:76-85).

    Unlike the target lib's, a null value IS returned (only an absent key
    falls back to the default).
    """
    if isinstance(obj, dict) and field in obj:
        return obj[field]
    return default


def constraint_spec(constraint: Dict[str, Any]) -> Any:
    return get_default(constraint, "spec", {})


def constraint_match(constraint: Dict[str, Any]) -> Any:
    return get_default(constraint_spec(constraint), "match", {})


def enforcement_action(constraint: Dict[str, Any]) -> Any:
    spec = hook_get_default(constraint, "spec", {})
    return hook_get_default(spec, "enforcementAction", "deny")


def constraint_parameters(constraint: Dict[str, Any]) -> Any:
    spec = hook_get_default(constraint, "spec", {})
    return hook_get_default(spec, "parameters", {})


def rego_scalar_eq(a: Any, b: Any) -> bool:
    """Rego equality for scalars: true != 1 (unlike Python), 1.0 == 1."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b
