"""Constraint-framework error taxonomy (mirrors client/errors.go)."""

from __future__ import annotations


class ConstraintFrameworkError(Exception):
    """Base class for all constraint framework errors."""


class MissingTemplateError(ConstraintFrameworkError):
    """Referenced ConstraintTemplate is not registered."""


class UnrecognizedConstraintError(ConstraintFrameworkError):
    """Constraint's kind does not match any registered template."""


class MissingConstraintError(ConstraintFrameworkError):
    """Constraint not found in the client cache."""


class InvalidTemplateError(ConstraintFrameworkError):
    """ConstraintTemplate failed structural or Rego validation."""


class InvalidConstraintError(ConstraintFrameworkError):
    """Constraint failed CRD-schema or target validation."""


class ErrorMap(ConstraintFrameworkError):
    """Aggregates per-target errors (client/errors.go ErrorMap)."""

    def __init__(self, errors):
        self.errors = dict(errors)
        msg = "; ".join(f"{k}: {v}" for k, v in sorted(self.errors.items()))
        super().__init__(msg)
