"""Evaluation engine drivers.

`Driver` is the engine plugin boundary — the same seven-method surface as
the reference's drivers.Driver interface
(vendor/.../frameworks/constraint/pkg/client/drivers/interface.go:21-39):
init / put_module(s) / delete_module(s) / put_data / delete_data / query /
dump. Everything above (Client, controllers, webhook, audit) is engine-
agnostic; swapping `RegoDriver` for the TPU driver changes nothing upstream.

`RegoDriver` is the CPU engine (reference counterpart:
drivers/local/local.go). Differences by design, not omission:
  * The constraint-matching + hook glue the reference evaluates as
    interpreted Rego (client/regolib/src.go, pkg/target's library) runs
    natively here via constraint.match — the interpreter only evaluates
    ConstraintTemplate `violation` rules.
  * Modules arrive as parsed, package-rewritten ASTs rather than source
    strings (the Client owns the compile pipeline), so there is no
    whole-universe recompile on template change (local.go:168-207's hot
    spot); module sets are mounted/unmounted incrementally.

Queries understood: `hooks["<target>"].violation` (admission Review path,
client/regolib/src.go:23-42) and `hooks["<target>"].audit` (cached-state
cross-join, :45-62).
"""

from __future__ import annotations

import json
import re
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..rego import ast as A
from ..rego.interp import Interpreter
from . import hooks as H
from .handler import TargetHandler, default_handler
from .datastore import DataStore
from .templates import CONSTRAINT_GROUP
from .types import Response, Result

_HOOK_RE = re.compile(r'^hooks\["([^"]+)"\]\.(violation|audit)$')

# the autoreject message is also the marker the partition merge uses to
# keep autoreject results ahead of evaluation results (parallel/
# partition.py mirrors the monolithic emit order exactly)
AUTOREJECT_MSG = "Namespace is not cached in OPA."


def constraint_key(constraint: Dict[str, Any]) -> str:
    """The stable identity of a constraint — `<kind>/<name>` — used by
    the partition plane to address constraint subsets. `_constraints`'
    (kind, name) sort order makes the sorted key list the global result
    order partitioned dispatch merges back into."""
    meta = constraint.get("metadata") or {}
    return f"{constraint.get('kind', '?')}/{meta.get('name', '?')}"


def _match_token(handler: TargetHandler, constraint: Dict[str, Any]) -> str:
    """Canonical signature of a constraint's match block. Two constraints
    with equal tokens match exactly the same reviews (matches_constraint
    is a pure function of the match IR), so the token is both the
    partition planner's locality group and the mask screen's dedup key."""
    try:
        return json.dumps(
            handler.match_ir(constraint), sort_keys=True, default=str
        )
    except Exception:
        return f"!opaque:{constraint_key(constraint)}"


def _autoreject_result(constraint: Dict[str, Any], review: Any) -> Result:
    """The autoreject Result shape (client/regolib/src.go:7-21) — the ONE
    definition shared by every evaluation path (serial interpreter,
    adaptive small-batch, fused device batch): driver parity demands the
    shape can never diverge between routes."""
    return Result(
        msg=AUTOREJECT_MSG,
        metadata={"details": {}},
        constraint=constraint,
        review=review,
        enforcement_action=H.enforcement_action(constraint),
    )


class Driver(ABC):
    """Engine plugin interface (drivers/interface.go:21-39)."""

    @abstractmethod
    def init(self) -> None: ...

    @abstractmethod
    def put_module(self, name: str, module: A.Module) -> None: ...

    @abstractmethod
    def put_modules(self, prefix: str, modules: Sequence[A.Module]) -> None: ...

    @abstractmethod
    def delete_module(self, name: str) -> bool: ...

    @abstractmethod
    def delete_modules(self, prefix: str) -> int: ...

    @abstractmethod
    def put_data(self, path: str, data: Any) -> None: ...

    @abstractmethod
    def delete_data(self, path: str) -> bool: ...

    @abstractmethod
    def query(
        self, path: str, input: Any = None, tracing: bool = False
    ) -> Response: ...

    def query_many(
        self, path: str, inputs: Sequence[Any], tracing: bool = False
    ) -> List[Response]:
        """Batched query: engines without a batch path evaluate serially;
        the TPU driver overrides this with one fused dispatch (the
        micro-batching webhook's entry point)."""
        return [self.query(path, i, tracing) for i in inputs]

    def query_host(
        self, path: str, input: Any = None, subset=None
    ) -> Response:
        """Host-only query: the degraded rung of the admission ladder
        (docs/robustness.md). Engines whose `query` already runs on the
        host inherit this; the TPU driver overrides it to pin the
        evaluation to the interpreter so a faulted device is never paid
        a second doomed attempt. `subset` (constraint keys, see
        `constraint_key`) scopes the evaluation to one partition's
        constraints — the fault-domain degraded rung evaluates ONLY the
        failed partition's subset on the host."""
        return self.query(path, input)

    def query_many_subset(
        self, path: str, inputs: Sequence[Any], subset, device: int = 0
    ) -> List[Response]:
        """Partition-scoped batched query (docs/robustness.md §Fault
        domains): evaluate only `subset`'s constraints for every input.
        Engines without a device path evaluate the subset serially on
        the host; the TPU driver overrides with a fused sub-program
        dispatch placed on logical `device`."""
        return [self.query_host(path, i, subset=subset) for i in inputs]

    @abstractmethod
    def dump(self) -> str: ...


def _module_prefix(prefix: str, idx: int) -> str:
    return f"{prefix}_idx_{idx}"


class RegoDriver(Driver):
    """CPU reference engine: interpreter-evaluated templates + native hooks."""

    def __init__(self):
        self.storage = DataStore()
        self.interp = Interpreter()
        self._module_names: Dict[str, List[str]] = {}  # prefix -> names
        # serializes module/data mutation against queries — the coarse
        # equivalent of the reference driver's modulesMux RWMutex
        # (drivers/local/local.go:63)
        self._mutex = threading.RLock()
        # frozen-inventory cache: freezing the external tree is O(corpus)
        # and would otherwise happen once per evaluated violation
        self._data_version = 0
        self._frozen_inv: Dict[str, Tuple[int, Any]] = {}
        # target name -> TargetHandler: the Client registers its
        # handlers here so the engine resolves match semantics through
        # the target boundary; unregistered names lazily resolve to the
        # K8s default (every pre-multi-target call site assumed it)
        self._target_handlers: Dict[str, TargetHandler] = {}

    def register_target(self, handler: TargetHandler) -> None:
        with self._mutex:
            self._target_handlers[handler.get_name()] = handler

    def _handler(self, target: str) -> TargetHandler:
        h = self._target_handlers.get(target)
        if h is None:
            h = self._target_handlers[target] = default_handler()
        return h

    def init(self) -> None:
        """No hook-library installation needed — hooks are native."""

    # -- module management --------------------------------------------------

    def put_module(self, name: str, module: A.Module) -> None:
        with self._mutex:
            self.interp.add_module(name, module)
            self._module_names.setdefault(name, [name])

    def put_modules(self, prefix: str, modules: Sequence[A.Module]) -> None:
        with self._mutex:
            self._delete_modules_locked(prefix)
            names = []
            for i, mod in enumerate(modules):
                name = _module_prefix(prefix, i)
                self.interp.add_module(name, mod)
                names.append(name)
            self._module_names[prefix] = names

    def delete_module(self, name: str) -> bool:
        with self._mutex:
            names = self._module_names.pop(name, None)
            if not names:
                return False
            for n in names:
                self.interp.remove_module(n)
            return True

    def delete_modules(self, prefix: str) -> int:
        with self._mutex:
            return self._delete_modules_locked(prefix)

    def _delete_modules_locked(self, prefix: str) -> int:
        names = self._module_names.pop(prefix, None)
        if not names:
            return 0
        for n in names:
            self.interp.remove_module(n)
        return len(names)

    # -- data management ----------------------------------------------------

    def put_data(self, path: str, data: Any) -> None:
        with self._mutex:
            self.storage.put(path, data)
            self._data_version += 1

    def delete_data(self, path: str) -> bool:
        with self._mutex:
            existed = self.storage.delete(path)
            self._data_version += 1
            return existed

    # -- query ---------------------------------------------------------------

    def query(
        self, path: str, input: Any = None, tracing: bool = False
    ) -> Response:
        m = _HOOK_RE.match(path)
        if not m:
            raise ValueError(f"unsupported query path: {path!r}")
        target, hook = m.group(1), m.group(2)
        trace_lines: Optional[List[str]] = [] if tracing else None
        t0 = time.perf_counter()
        with self._mutex:
            if hook == "violation":
                results = self._violation(target, input or {}, trace_lines)
            else:
                results = self._audit(target, trace_lines)
        resp = Response(target=target, results=results)
        if tracing:
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            trace_lines.append(f"eval done: {len(results)} results in {elapsed_ms:.2f}ms")
            resp.trace = "\n".join(trace_lines)
            resp.input = json.dumps(input, default=str, sort_keys=True)
        return resp

    # -- hook implementations ------------------------------------------------

    def _constraints(self, target: str) -> List[Dict[str, Any]]:
        """All constraints, ordered (kind, name) — matching OPA's sorted-set
        iteration over data.constraints.<target>.cluster[group][kind][name]."""
        tree = self.storage.get(
            ["constraints", target, "cluster", CONSTRAINT_GROUP], {}
        )
        out: List[Dict[str, Any]] = []
        if not isinstance(tree, dict):
            return out
        for kind in sorted(tree):
            by_name = tree[kind]
            if not isinstance(by_name, dict):
                continue
            for name in sorted(by_name):
                c = by_name[name]
                if isinstance(c, dict):
                    out.append(c)
        return out

    def constraint_keys(self, target: str) -> List[str]:
        """Sorted `<kind>/<name>` identities of every constraint — the
        global order partitioned dispatch merges back into, and the
        corpus a PartitionPlan splits (parallel/partition.py)."""
        with self._mutex:
            return [constraint_key(c) for c in self._constraints(target)]

    def constraint_generation(self) -> int:
        """Monotonic constraint-churn signal: the partition plane
        rebuilds its plan when this moves. The base driver bumps its
        data version on every write (over-eager but sound); the TPU
        driver narrows it to actual constraint/template churn."""
        return self._data_version

    def constraint_locality(self, target: str) -> Dict[str, str]:
        """Match-locality token per constraint key. Constraints sharing
        a token are satisfied by exactly the same reviews, so the
        partition planner (parallel/partition.py build_plan) co-locates
        them: a batch whose reviews hit one locality group then touches
        one partition instead of all K."""
        with self._mutex:
            handler = self._handler(target)
            return {
                constraint_key(c): _match_token(handler, c)
                for c in self._constraints(target)
            }

    def constraint_costs(self, target: str) -> Dict[str, float]:
        """Relative per-constraint evaluation weight for the partition
        planner's load balancing. The interpreter has no compiled
        programs to size, so every constraint weighs the same; the TPU
        driver overrides this with the compiled program's static cost."""
        with self._mutex:
            return {
                constraint_key(c): 1.0 for c in self._constraints(target)
            }

    def _ns_cache(self, target: str) -> Dict[str, Any]:
        """The target's review-context cache (K8s: synced Namespaces);
        resolution is the handler's, the storage accessor ours."""
        return self._handler(target).review_context_cache(self.storage.get)

    def _inventory(self, target: str) -> Any:
        """inventory rule (client/regolib/src.go:66-71), pre-frozen and
        cached per data version (interp.make_context re-freezes in O(1)
        via the values.freeze Obj fast path)."""
        cached = self._frozen_inv.get(target)
        if cached is not None and cached[0] == self._data_version:
            return cached[1]
        from ..rego.values import freeze

        inv = self.storage.get(["external", target], None)
        frozen = freeze(inv if inv is not None else {})
        self._frozen_inv[target] = (self._data_version, frozen)
        return frozen

    def query_host(
        self, path: str, input: Any = None, subset=None
    ) -> Response:
        """Interpreter evaluation (this engine's query IS host-side),
        optionally scoped to a constraint subset (the fault-domain
        degraded rung: only the failed partition's constraints are
        re-evaluated on the host, docs/robustness.md §Fault domains)."""
        if subset is None:
            return self.query(path, input)
        m = _HOOK_RE.match(path)
        if m is None or m.group(2) != "violation":
            raise ValueError(f"unsupported subset query path: {path!r}")
        target = m.group(1)
        sub = frozenset(subset)
        with self._mutex:
            constraints = [
                c for c in self._constraints(target)
                if constraint_key(c) in sub
            ]
            results = RegoDriver._violation(
                self, target, input or {}, None, constraints=constraints
            )
        return Response(target=target, results=results)

    def partition_match_mask(
        self, path: str, inputs: Sequence[Any], subsets: Sequence[Any]
    ) -> List[List[bool]]:
        """Per-(partition, input) match screen: mask[p][i] is True iff
        input i could produce ANY result from subset p's constraints —
        a real match, or an autoreject against a needs-context
        constraint in the subset. The partitioned batcher uses it to
        skip partitions no request in the batch touches (a faulted
        partition whose constraints match nothing in the batch costs
        the batch NOTHING — the blast-radius contract) and to scope the
        degraded host rung to affected requests only."""
        m = _HOOK_RE.match(path)
        if m is None or m.group(2) != "violation":
            raise ValueError(f"unsupported mask query path: {path!r}")
        target = m.group(1)
        with self._mutex:
            handler = self._handler(target)
            constraints = self._constraints(target)
            ns_cache = self._ns_cache(target)
            # dedupe by match-block signature: a corpus stamped from a
            # few templates shares match blocks across hundreds of
            # constraints, so the screen costs O(distinct-blocks x batch)
            # instead of O(constraints x batch)
            key_toks: Dict[str, set] = {}
            rep: Dict[str, Dict[str, Any]] = {}
            for c in constraints:
                tok = _match_token(handler, c)
                key_toks.setdefault(constraint_key(c), set()).add(tok)
                rep.setdefault(tok, c)
            reviews = [
                H.hook_get_default(i or {}, "review", {}) for i in inputs
            ]
            autorej = [
                bool(constraints)
                and handler.review_autorejects(r, ns_cache)
                for r in reviews
            ]
            tok_hits = {
                tok: [
                    handler.matches_constraint(c, r, ns_cache)
                    for r in reviews
                ]
                for tok, c in rep.items()
            }
            tok_needs = {
                tok: handler.constraint_needs_context(c)
                for tok, c in rep.items()
            }
            masks: List[List[bool]] = []
            for subset in subsets:
                toks = {
                    t for k in subset for t in key_toks.get(k, ())
                }
                needs_ctx = any(tok_needs[t] for t in toks)
                hits = [tok_hits[t] for t in toks]
                masks.append([
                    (ar and needs_ctx) or any(h[i] for h in hits)
                    for i, ar in enumerate(autorej)
                ])
            return masks

    def _violation(
        self, target: str, input: Dict[str, Any],
        trace: Optional[List[str]],
        constraints: Optional[List[Dict[str, Any]]] = None,
    ) -> List[Result]:
        review = H.hook_get_default(input, "review", {})
        handler = self._handler(target)
        if constraints is None:
            constraints = self._constraints(target)
        ns_cache = self._ns_cache(target)
        inventory = self._inventory(target)
        results: List[Result] = []
        # autoreject factors (match.needs_ns_selector docstring): the
        # constraint half is handler.constraint_needs_context, the
        # review half handler.review_autorejects
        if constraints and handler.review_autorejects(review, ns_cache):
            for constraint in constraints:
                if handler.constraint_needs_context(constraint):
                    results.append(_autoreject_result(constraint, review))
                    if trace is not None:
                        trace.append(f"autoreject: {_cname(constraint)}")
        for constraint in constraints:
            if not handler.matches_constraint(constraint, review, ns_cache):
                if trace is not None:
                    trace.append(f"no match: {_cname(constraint)}")
                continue
            results.extend(
                self._eval_template(
                    target, constraint, review, inventory, trace
                )
            )
        return results

    def _audit(self, target: str, trace: Optional[List[str]]) -> List[Result]:
        handler = self._handler(target)
        constraints = self._constraints(target)
        if not constraints:
            return []
        ns_cache = self._ns_cache(target)
        inventory = self._inventory(target)
        external = self.storage.get(["external", target], {})
        results: List[Result] = []
        for review in handler.iter_cached_reviews(external):
            for constraint in constraints:
                if not handler.matches_constraint(
                    constraint, review, ns_cache
                ):
                    continue
                results.extend(
                    self._eval_template(
                        target, constraint, review, inventory, trace
                    )
                )
        return results

    def _eval_template(
        self,
        target: str,
        constraint: Dict[str, Any],
        review: Any,
        inventory: Any,
        trace: Optional[List[str]],
        frozen_review: Any = None,
    ) -> List[Result]:
        """`frozen_review`: callers rendering MANY constraints against
        one review pre-freeze it once (values.freeze re-freezes frozen
        Objs in O(1)); freeze was ~30% of per-pair render time."""
        kind = constraint.get("kind")
        if not isinstance(kind, str):
            return []
        input_doc = {
            "review": review if frozen_review is None else frozen_review,
            "parameters": H.constraint_parameters(constraint),
        }
        violations = self.interp.query_violations(
            ["templates", target, kind], input_doc, {"inventory": inventory}
        )
        enforcement = H.enforcement_action(constraint)
        out: List[Result] = []
        for v in violations:
            if not isinstance(v, dict) or "msg" not in v:
                # the hook rule body references r.msg; violations without a
                # msg field are undefined there and silently dropped
                continue
            out.append(
                Result(
                    msg=v["msg"],
                    metadata={"details": H.hook_get_default(v, "details", {})},
                    constraint=constraint,
                    review=review,
                    enforcement_action=enforcement,
                )
            )
        if trace is not None:
            trace.append(f"eval {_cname(constraint)}: {len(out)} violation(s)")
        return out

    # -- dump ----------------------------------------------------------------

    def dump(self) -> str:
        return json.dumps(
            {
                "data": json.loads(self.storage.dump_json()),
                "modules": sorted(
                    n for names in self._module_names.values() for n in names
                ),
            },
            indent=2,
            sort_keys=True,
            default=str,
        )


def _cname(constraint: Dict[str, Any]) -> str:
    meta = constraint.get("metadata") or {}
    return f"{constraint.get('kind', '?')}/{meta.get('name', '?')}"
