"""Admission webhook layer.

Counterpart of pkg/webhook/: the validating handler (policy.go), the
namespace-label guard (namespacelabel.go), and — new to the TPU build —
the micro-batching bridge that coalesces concurrent AdmissionReviews
into one fused device dispatch (SURVEY §2.4 row 3).
"""

from .policy import AdmissionResponse, TraceConfig, ValidationHandler  # noqa: F401
from .certs import CertRotator  # noqa: F401
from .namespacelabel import IGNORE_LABEL, NamespaceLabelHandler  # noqa: F401
from .server import MicroBatcher, WebhookServer  # noqa: F401
