"""Admission webhook layer.

Counterpart of pkg/webhook/: the validating handler (policy.go), the
namespace-label guard (namespacelabel.go), the mutating handler
(mutate.py over gatekeeper_tpu/mutation/), and — new to the TPU build —
the micro-batching bridge that coalesces concurrent AdmissionReviews
into one fused device dispatch (SURVEY §2.4 row 3) on BOTH planes
(validate: Client.review_many; mutate: MutationSystem.screen).
"""

from .policy import AdmissionResponse, TraceConfig, ValidationHandler  # noqa: F401
from .certs import CertRotator  # noqa: F401
from .namespacelabel import IGNORE_LABEL, NamespaceLabelHandler  # noqa: F401
from .server import MicroBatcher, WebhookServer, review_envelope  # noqa: F401
from .mutate import MutateBatcher, MutationHandler  # noqa: F401
