"""Micro-batching admission server.

The reference webhook evaluates one AdmissionReview per goroutine behind
a shared RWMutex (pkg/webhook/policy.go:141, drivers/local/local.go:303)
— concurrency without batching. The TPU path inverts that: concurrent
requests are coalesced for up to `window_ms` (or until `max_batch`) and
the whole batch is evaluated in ONE fused device dispatch via
`Client.review_many` (SURVEY §2.4 row 3's micro-batching bridge).

`WebhookServer` is a stdlib HTTP server serving /v1/admit and
/v1/admitlabel with AdmissionReview JSON — the in-process stand-in for
the Go webhook pod. With `tls=True` it terminates HTTPS with a
rotating self-signed CA + server cert (`certs.CertRotator`, the
pkg/webhook/certs.go counterpart).

Failure semantics preserve the reference's fail-open design (SURVEY §5)
and make the whole degradation ladder explicit (docs/robustness.md):
a failed fused batch falls back to per-request HOST-interpreter
evaluation (never a second doomed device attempt), a circuit breaker
(`faults.CircuitBreaker`) short-circuits the fused path entirely after
K consecutive batch failures, and only a request whose own host
evaluation also fails gets an error response — one poisoned request can
no longer 500 a whole batch. Overload protection: the admission queue
is bounded (`max_queue`) with load shedding, and requests carry their
caller deadline so an already-expired request is shed before dispatch
instead of evaluated and discarded. Shed/degraded requests get the
endpoint's fail-open/fail-closed envelope, not a raw 500.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..faults import (
    CircuitBreaker,
    DeadlineExceeded,
    EvaluationTimeout,
    EvaluationUnavailable,
    FaultError,
    ShedError,
    fire,
    skew,
)
from .namespacelabel import NamespaceLabelHandler
from .policy import AdmissionResponse, ValidationHandler

# the K8s webhook timeoutSeconds ceiling is 30s and Gatekeeper deploys
# with 3s; our per-request deadline stays safely under the ceiling
DEFAULT_REQUEST_TIMEOUT = 10.0

# bounded admission queue: at max_batch=256 and low-ms batch drains this
# is seconds of backlog — anything deeper is already past every caller
# deadline, so evaluating it would be pure waste (shed instead)
DEFAULT_MAX_QUEUE = 2048


def review_envelope(
    review: Dict[str, Any], request: Dict[str, Any], resp,
    trace_id: Optional[str] = None,
) -> Dict[str, Any]:
    """The one AdmissionReview response envelope, shared by every
    endpoint (admit / admitlabel / mutate): echoes the request's
    apiVersion (falling back to admission/v1) and uid, and carries the
    handler's response dict — including `patchType`/`patch` when the
    response has one — so the three endpoints can never drift. With a
    trace id (inbound `traceparent` or the admission-UID derivation)
    the envelope echoes it as `traceId`, so the caller can join its
    admission verdict to `/debug/traces?trace_id=` and the denial log
    without guessing (docs/observability.md §Trace propagation)."""
    out = {
        "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": resp.to_dict(uid=request.get("uid")),
    }
    if trace_id is not None:
        out["traceId"] = trace_id
    return out


class MicroBatcher:
    """Collects admission requests into batches for fused evaluation.

    submit() returns a Future resolving to the request's results list.
    A background worker drains the queue every `window_ms` (or as soon
    as `max_batch` requests are pending) and runs one
    `Client.review_many` call for the whole batch.
    """

    # the plane tag on shed/breaker/queue metrics (MutateBatcher
    # overrides with "mutation")
    plane = "validation"

    def __init__(
        self,
        client,
        target: str,
        window_ms: float = 2.0,
        max_batch: int = 256,
        namespace_getter: Optional[Callable[[str], Optional[dict]]] = None,
        metrics=None,
        tracer=None,
        # bounded admission queue (overload shedding); None = unbounded
        max_queue: Optional[int] = DEFAULT_MAX_QUEUE,
        # device circuit breaker: None = construct the default; False =
        # disabled; or pass a faults.CircuitBreaker to share/observe
        breaker=None,
        # device fault domains (docs/robustness.md §Fault domains): a
        # parallel.partition.PartitionDispatcher replaces the single
        # per-plane breaker with per-(device, plane) breakers — batches
        # fan out over constraint-subset partitions, a failed partition
        # degrades ONLY its subset to the host rung, and quarantined
        # devices re-home their partitions onto healthy ones
        partitioner=None,
        # obs.FlightRecorder: shed bursts (and the default breaker's
        # OPEN transitions) trip postmortem captures
        recorder=None,
        # obs.DecisionLog: the batch worker stashes per-request
        # dispatch facts (route, partition set dispatched vs
        # mask-skipped, rows_dispatched/rows_total, cache/fetch
        # counts, device-time share) under each request's trace id;
        # the handler claims them when it records the decision
        # (docs/observability.md §Decision log)
        decisions=None,
        # admission scheduling (docs/operations.md §Admission
        # scheduling): "fifo" is bit-compatible with the pre-scheduler
        # queue; "deadline" turns on EDF batch formation, predictive
        # shedding, and per-tenant fair-share quotas. slo (SloEngine)
        # feeds the overload/saturation loop and the batch cost EWMA;
        # attributor seeds the cost model before the EWMA warms.
        sched_policy: str = "fifo",
        slo=None,
        attributor=None,
        # integrity.IntegrityPlane: post-response shadow-oracle
        # sampling — a deterministic CRC(trace_id) fraction of live
        # admissions re-evaluates asynchronously on the host
        # interpreter (docs/robustness.md §Verdict integrity)
        integrity=None,
    ):
        self.client = client
        self.target = target
        self.partitioner = partitioner
        self.recorder = recorder
        self.decisions = decisions
        self.integrity = integrity
        # (constraint generation, corpus size) cache for rows facts
        self._rows_cache: Optional[Tuple[Any, int]] = None
        if partitioner is not None and breaker is None:
            # the per-device breaker bank replaces the plane breaker
            breaker = False
        # the target handler owns serving-plane review construction
        # (K8s: AdmissionRequest -> AugmentedReview; agent: tool-call
        # record -> AgentAction); client=None planes (MutateBatcher)
        # build their own reviews in _dispatch, and clients without a
        # target registry (test fakes) get the default handler
        if client is not None:
            from ..constraint.handler import handler_for

            self.target_handler = handler_for(client, target)
        else:
            self.target_handler = None
        self.window = window_ms / 1000.0
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.namespace_getter = namespace_getter
        self.metrics = metrics
        # obs.Tracer: the batch worker stamps queue-wait + dispatch +
        # render spans into EVERY member request's trace (the shared
        # batch window, recorded per trace so each is self-contained)
        self.tracer = tracer
        if breaker is None:
            breaker = CircuitBreaker(
                plane=self.plane, metrics=metrics, tracer=tracer,
                recorder=recorder,
            )
        self.breaker: Optional[CircuitBreaker] = breaker or None
        # the admission scheduler owns enqueue-side admit/shed and the
        # dispatch-side batch cut; its clock is the batcher's skewed
        # deadline clock so chaos clock jumps steer it too
        from ..sched import AdmissionScheduler

        self.sched = AdmissionScheduler(
            plane=self.plane,
            policy=sched_policy,
            max_queue=max_queue,
            clock=self._now,
            slo=slo,
            attributor=attributor,
            metrics=metrics,
        )
        # (request, future, span ctx | None, (wall, perf) submit stamp,
        #  monotonic deadline | None, scheduler tenant key | None)
        self._pending: List[
            Tuple[Dict[str, Any], Future, Any, Tuple, Optional[float],
                  Optional[str]]
        ] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.batches_dispatched = 0
        self.requests_batched = 0
        self.batch_failures = 0
        self.shed_count = 0

    def start(self) -> None:
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # in-flight requests must not hang until their caller timeout:
        # dispatch whatever the worker left behind
        with self._lock:
            leftover = self._pending
            self._pending = []
        if leftover:
            self._dispatch(leftover)

    def _now(self) -> float:
        """The batcher's deadline clock: monotonic plus any injected
        clock-jump skew (fault point `webhook.clock`) so chaos runs can
        simulate NTP steps without touching the real clock."""
        return time.monotonic() + skew("webhook.clock")

    # -- decision facts (docs/observability.md §Decision log) ----------------

    def _corpus_rows(self) -> Optional[int]:
        """Constraint-corpus size (the rows_total denominator), cached
        per constraint generation so the hot path pays one generation
        read per batch, not a key listing."""
        drv = getattr(self.client, "_driver", None) if self.client else None
        keys_fn = getattr(drv, "constraint_keys", None)
        if keys_fn is None:
            return None
        gen_fn = getattr(drv, "constraint_generation", None)
        gen = gen_fn() if gen_fn is not None else None
        cached = self._rows_cache
        if cached is None or cached[0] != gen:
            try:
                cached = self._rows_cache = (gen, len(keys_fn(self.target)))
            except Exception:
                return None
        return cached[1]

    def _driver_route(self, n: int) -> str:
        """What the driver will actually do with an n-request batch:
        `fused` (device dispatch) or `interp` (the adaptive small-batch
        / cold-compile interpreter route) — the route fact a decision
        record explains a request's latency with."""
        drv = getattr(self.client, "_driver", None) if self.client else None
        if drv is None or not getattr(drv, "use_jax", False):
            return "interp"
        from ..constraint import tpudriver as _td

        warm_fn = getattr(drv, "review_path_warm", None)
        warm = warm_fn(self.target) if warm_fn is not None else True
        if n < _td.MIN_DEVICE_BATCH or not warm:
            return "interp"
        return "fused"

    def _note_rows(self, partition, rows_dispatched, rows_total) -> None:
        """The pruning-efficiency series (ROADMAP item 1's instrument):
        constraint-rows actually dispatched vs the full corpus, per
        partition — `dispatch_efficiency = dispatched/total` falling
        with constraint count is what batch-aware pruned dispatch will
        be judged by."""
        if self.metrics is None or not rows_total:
            return
        self.metrics.record(
            "dispatch_rows_dispatched_total", rows_dispatched,
            plane=self.plane, partition=str(partition),
        )
        self.metrics.record(
            "dispatch_rows_total", rows_total,
            plane=self.plane, partition=str(partition),
        )

    def _driver_consumption(self) -> Dict[str, Any]:
        """Per-batch consumption facts from the driver's last dispatch
        stats: render-cache hits and the batch's device-execute window
        (apportioned per request by the caller)."""
        drv = getattr(self.client, "_driver", None) if self.client else None
        stats = getattr(drv, "stats", None)
        out: Dict[str, Any] = {}
        if isinstance(stats, dict):
            if "render_cache_hits" in stats:
                out["cache_hits"] = stats["render_cache_hits"]
            phases = stats.get("phase_seconds") or {}
            if "device_dispatch" in phases:
                out["device_seconds"] = phases["device_dispatch"]
        return out

    def _note_decisions(
        self, batch, route: str, rows_dispatched=None, rows_total=None,
        extdata_fetches: Optional[int] = None, per_request=None,
        columns_skipped_static: Optional[int] = None,
    ) -> None:
        """Stash dispatch facts for every traced member request. Batch-
        shared facts (route, rows, fetches, device share) apply to all;
        `per_request` maps batch index -> overriding facts (the
        partitioned path's per-request partition sets)."""
        if self.decisions is None:
            return
        cons = self._driver_consumption()
        dev = cons.pop("device_seconds", None)
        base: Dict[str, Any] = {"route": route, "batch_size": len(batch)}
        base.update(cons)
        if rows_total is not None:
            base["rows_total"] = rows_total
            base["rows_dispatched"] = (
                rows_dispatched if rows_dispatched is not None
                else rows_total
            )
        if extdata_fetches is not None:
            base["extdata_fetches"] = extdata_fetches
        if columns_skipped_static is not None:
            # dead token slots the IR liveness mask dropped from this
            # batch's encode (docs/analysis.md §IR analysis)
            base["columns_skipped_static"] = columns_skipped_static
        if dev is not None and batch:
            # the batch's measured device window split evenly across
            # members — the request-level share of what the constraint-
            # level CostAttributor accounts exactly
            base["device_seconds_share"] = round(dev / len(batch), 9)
        for i, (_, _, ctx, _, _, _) in enumerate(batch):
            tid = getattr(ctx, "trace_id", None)
            if tid is None:
                continue
            facts = base
            if per_request is not None and i in per_request:
                facts = dict(base)
                facts.update(per_request[i])
            self.decisions.note_dispatch(tid, **facts)

    def _extdata_fetch_count(self) -> int:
        ed = getattr(self.client, "external_data", None) if self.client \
            else None
        return int(getattr(ed, "fetch_count", 0) or 0)

    def _liveness_skipped_count(self) -> int:
        """Driver-cumulative count of provably-dead token slots the IR
        feature-liveness mask dropped before padding (analysis/ir.py);
        dispatch sites report the per-batch delta as a decision fact."""
        drv = getattr(self.client, "_driver", None) if self.client else None
        return int(getattr(drv, "columns_skipped_static", 0) or 0)

    def _shed(self, fut: Future, exc: Exception, reason: str,
              ctx=None, sub_wall: Optional[float] = None) -> None:
        """Resolve a future without evaluation: counted, traced, and
        typed so the handler answers with the fail policy envelope."""
        with self._lock:  # sheds race from concurrent submit threads
            self.shed_count += 1
        if self.metrics is not None:
            self.metrics.record(
                "webhook_shed_total", 1, plane=self.plane, reason=reason
            )
        if self.tracer is not None and ctx is not None:
            now = time.time()
            self.tracer.record_span(
                "shed", sub_wall if sub_wall is not None else now, now,
                parent=ctx, reason=reason, plane=self.plane,
            )
        if self.recorder is not None:
            # shed-burst detection: the recorder counts; crossing its
            # threshold trips ONE postmortem capture for the storm
            try:
                self.recorder.note_shed(self.plane)
            except Exception:
                pass
        fut.set_exception(exc)

    def submit(self, request: Dict[str, Any], span_ctx=None,
               deadline: Optional[float] = None, tenant=None) -> Future:
        """Enqueue for the next fused dispatch. `deadline` is a
        monotonic timestamp (the caller's remaining budget): a request
        that is already expired — or expires while queued — is shed
        with DeadlineExceeded instead of ever reaching a dispatch.
        `tenant` is the decision-log tenant identity, extracted BEFORE
        enqueue so shed verdicts carry it and the scheduler's
        fair-share quotas account exactly."""
        fut: Future = Future()
        stamp = (time.time(), time.perf_counter())
        if deadline is not None and self._now() >= deadline:
            # expired before enqueue: never pay queue + dispatch for an
            # answer nobody is waiting for
            self._shed(
                fut,
                DeadlineExceeded("request deadline expired before enqueue"),
                "deadline", ctx=span_ctx, sub_wall=stamp[0],
            )
            return fut
        shed_exc = victim_item = victim_exc = None
        with self._lock:
            stopped = self._stop
            if not stopped:
                key, shed_exc, victim = self.sched.offer(
                    self._pending, tenant=tenant, deadline=deadline,
                    now=self._now(),
                )
                if shed_exc is None:
                    if victim is not None:
                        # predictive shedding under a full queue: the
                        # queued request that provably cannot make its
                        # deadline goes, not the viable newcomer
                        idx, victim_exc = victim
                        victim_item = self._pending.pop(idx)
                    self._pending.append(
                        (request, fut, span_ctx, stamp, deadline, key)
                    )
                    n = len(self._pending)
        if stopped:
            # worker is gone (and stop() may have already drained its
            # leftovers): dispatch inline so the caller never hangs
            self._dispatch(
                [(request, fut, span_ctx, stamp, deadline, None)]
            )
            return fut
        if victim_item is not None:
            _, vfut, vctx, vstamp, _, _ = victim_item
            self._shed(
                vfut, victim_exc, victim_exc.reason,
                ctx=vctx, sub_wall=vstamp[0],
            )
        if shed_exc is not None:
            self._shed(
                fut, shed_exc, getattr(shed_exc, "reason", "queue_full"),
                ctx=span_ctx, sub_wall=stamp[0],
            )
        else:
            if self.metrics is not None:
                self.metrics.gauge(
                    "admission_queue_depth", n, plane=self.plane
                )
            if n == 1 or n >= self.max_batch:
                self._wake.set()
        return fut

    def _loop(self) -> None:
        while True:
            # idle: block until the first request (or stop) arrives —
            # no fixed-cadence wakeups while the queue is empty
            self._wake.wait()
            self._wake.clear()
            if self._stop:
                return
            # a batch has started forming: coalesce for up to `window`,
            # cut short when max_batch fills
            deadline = time.monotonic() + self.window
            while not self._stop:
                with self._lock:
                    n = len(self._pending)
                remaining = deadline - time.monotonic()
                if n >= self.max_batch or remaining <= 0:
                    break
                self._wake.wait(remaining)
                self._wake.clear()
            with self._lock:
                # the scheduler cuts the batch: fifo takes everything
                # in arrival order (the pre-scheduler swap); deadline
                # policy orders EDF and defers requests that would blow
                # the earliest member deadline to the next window
                batch, rest = self.sched.cut(
                    self._pending, self.max_batch, now=self._now()
                )
                self._pending = rest
            if rest:
                # deferred work exists: start the next window now
                self._wake.set()
            if self.metrics is not None:
                self.metrics.gauge(
                    "admission_queue_depth", len(rest), plane=self.plane
                )
            if batch:
                self._dispatch(batch)
            if self._stop:
                return

    def _strip_expired(self, batch):
        """Deadline propagation: requests whose caller deadline expired
        while queued are shed here — before any dispatch — instead of
        evaluated and discarded."""
        now = self._now()
        live = []
        for item in batch:
            _, fut, ctx, stamp, deadline = item[:5]
            if deadline is not None and now >= deadline:
                self._shed(
                    fut,
                    DeadlineExceeded(
                        "request deadline expired while queued"
                    ),
                    "deadline", ctx=ctx, sub_wall=stamp[0],
                )
            else:
                live.append(item)
        return live

    def _dispatch(self, batch) -> None:
        batch = self._strip_expired(batch)
        if not batch:
            return
        wall0, t0 = time.time(), time.perf_counter()
        reviews = [
            self.target_handler.augment_request(
                request, self.namespace_getter
            )
            for request, _, _, _, _, _ in batch
        ]
        if self.partitioner is not None:
            plan = None
            try:
                plan = self.partitioner.plan()
            except Exception:
                plan = None  # plan failure: monolithic path still serves
            if plan is not None and plan.partitions:
                self._dispatch_partitioned(batch, reviews, plan, wall0, t0)
                return
        breaker = self.breaker
        if breaker is not None and not breaker.allow():
            # breaker open: the fused path has been failing — go
            # straight to the host-interpreter degraded mode, paying
            # zero doomed device attempts for this batch
            if self.metrics is not None:
                self.metrics.record(
                    "webhook_degraded_dispatch_total", 1, plane=self.plane
                )
            self._dispatch_host(batch, reviews, wall0, t0, route="degraded")
            return
        fetch0 = self._extdata_fetch_count()
        skip0 = self._liveness_skipped_count()
        try:
            fire("webhook.batch_dispatch")
            all_responses = self.client.review_many(reviews)
        except Exception:
            # fused-path failure: degrade to the host-oracle rung so
            # one poisoned request (or a device fault) cannot fail the
            # whole batch — requests still get correct answers and only
            # their own failure surfaces to them
            if breaker is not None:
                breaker.record_failure()
            self.batch_failures += 1
            if self.metrics is not None:
                self.metrics.record("webhook_batch_failures_total", 1)
            self._dispatch_host(batch, reviews, wall0, t0, route="fallback")
            return
        if breaker is not None:
            breaker.record_success()
        self.batches_dispatched += 1
        self.requests_batched += len(batch)
        if self.metrics is not None:
            self.metrics.record("webhook_batches_total", 1)
            self.metrics.observe("webhook_batch_size", len(batch))
        self._record_spans(batch, wall0, t0, route="batched")
        # dispatch-explain facts: the monolithic dispatch evaluates the
        # whole corpus for every member (no pruning: dispatched == total
        # under the "mono" partition label)
        rows = self._corpus_rows()
        rows_total = rows * len(batch) if rows is not None else None
        if rows_total is not None:
            self._note_rows("mono", rows_total, rows_total)
        self._note_decisions(
            batch, self._driver_route(len(reviews)),
            rows_dispatched=rows, rows_total=rows,
            extdata_fetches=self._extdata_fetch_count() - fetch0,
            columns_skipped_static=(
                self._liveness_skipped_count() - skip0
            ),
        )
        for (_, fut, ctx, _, _, _), review, responses in zip(
            batch, reviews, all_responses
        ):
            resp = responses.by_target.get(self.target)
            results = resp.results if resp is not None else []
            fut.set_result(results)
            self._note_integrity(ctx, review, results, route="batched")

    def _note_integrity(self, ctx, review, results, **facts) -> None:
        """Offer one served admission to the verdict-integrity plane's
        shadow oracle (CRC-sampled, asynchronous, post-response —
        docs/robustness.md §Verdict integrity). Never fails a request."""
        if self.integrity is None:
            return
        try:
            self.integrity.note_live(
                getattr(ctx, "trace_id", None), review, results,
                plane=self.plane, **facts,
            )
        except Exception:
            pass

    @staticmethod
    def _ensure_staged_nowait(part, p) -> bool:
        """ensure_staged without blocking the admission batch on a
        compile: churned sub-programs restage in the background while
        this batch serves from the host rung (docs/compile.md). The
        TypeError fallback keeps older/duck-typed dispatchers working."""
        try:
            return part.ensure_staged(p, wait=False)
        except TypeError:
            return part.ensure_staged(p)

    def _dispatch_partitioned(self, batch, reviews, plan,
                              wall0: float, t0: float) -> None:
        """Fault-domain dispatch (docs/robustness.md §Fault domains):
        fan the batch out over the plan's constraint-subset partitions,
        each gated by its device's breaker. A failed/open partition
        degrades ONLY its constraint subset — and only the requests
        that subset matches — to the host-interpreter rung; healthy
        partitions stay fused. Merged results are bit-identical to the
        monolithic dispatch (the partition parity battery pins it)."""
        from ..parallel.partition import merge_partition_results

        part = self.partitioner
        client = self.client
        if plan.all_dead:
            # the whole device fleet is quarantined: fall back to the
            # existing whole-plane host mode — and still run probes, or
            # nothing would ever bring a device back
            if self.metrics is not None:
                self.metrics.record(
                    "webhook_degraded_dispatch_total", 1, plane=self.plane
                )
            self._dispatch_host(batch, reviews, wall0, t0, route="degraded")
            part.run_probes(reviews)
            return
        try:
            fire("webhook.batch_dispatch")
        except Exception:
            # a whole-plane fault (the unlabeled point): every device
            # pays a failure — this is the pre-partition behavior and
            # keeps existing chaos scenarios meaningful
            for p in plan.partitions:
                part.breaker(p.device).record_failure()
            self.batch_failures += 1
            if self.metrics is not None:
                self.metrics.record("webhook_batch_failures_total", 1)
            self._dispatch_host(batch, reviews, wall0, t0, route="fallback")
            part.run_probes(reviews)
            return
        fetch0 = self._extdata_fetch_count()
        skip0 = self._liveness_skipped_count()
        prefetch = getattr(client, "prefetch_external", None)
        if prefetch is not None:
            # one deduped external-data fetch epoch for the whole batch
            # (every partition dispatch then serves from the cache)
            try:
                prefetch(reviews)
            except Exception:
                pass
        try:
            masks = client.partition_match_mask(
                reviews, [p.subset for p in plan.partitions]
            )
        except Exception:
            # sound fallback: every partition sees every request
            masks = [[True] * len(reviews) for _ in plan.partitions]
        fused: List[Any] = []
        host_parts: List[Any] = []
        skipped_parts: List[int] = []
        for p, mask in zip(plan.partitions, masks):
            if not any(mask):
                # nothing in this batch touches the partition: zero
                # cost, zero degraded dispatches — the blast-radius
                # contract for requests matching only healthy subsets
                part.note_dispatch("skipped", p.device)
                skipped_parts.append(p.index)
                continue
            br = part.breaker(p.device)
            if not br.allow():
                if self.metrics is not None:
                    self.metrics.record(
                        "webhook_degraded_dispatch_total", 1,
                        plane=self.plane,
                    )
                host_parts.append(p)
            elif not self._ensure_staged_nowait(part, p):
                # restage not complete (re-home backoff, or a churned
                # sub-program compiling in the background,
                # docs/compile.md): host rung — correct verdicts from
                # the interpreter, NOT a degraded dispatch — until the
                # swap lands
                host_parts.append(p)
            else:
                fused.append((p, br))

        # mask-sliced dispatch: each partition evaluates ONLY the
        # requests its mask row selects — the requests that can produce
        # a result from its subset. Unselected rows contribute zero
        # results by the mask's definition, so padding them back as
        # empty lists keeps merged verdicts bit-identical to the
        # monolith while rows_dispatched drops to the matched cells.
        sel_by_part = {
            p.index: [i for i, hit in enumerate(masks[p.index]) if hit]
            for p in plan.partitions
        }

        def run_one(p, br):
            sel = sel_by_part[p.index]
            try:
                return p, br, client.review_many_subset(
                    [reviews[i] for i in sel], p.subset,
                    device=p.device, partition=p.index,
                ), None
            except Exception as e:
                return p, br, None, e

        executor = part.executor if len(fused) > 1 else None
        if executor is not None:
            outcomes = list(executor.map(lambda a: run_one(*a), fused))
        else:
            outcomes = [run_one(p, br) for p, br in fused]
        # partition index -> per-request result lists
        part_results: Dict[int, List[List[Any]]] = {}
        for p, br, resps, exc in outcomes:
            if exc is None:
                br.record_success()
                part.note_dispatch("fused", p.device)
                rows: List[List[Any]] = [[] for _ in reviews]
                for i, responses in zip(sel_by_part[p.index], resps):
                    resp = responses.by_target.get(self.target)
                    rows[i] = resp.results if resp is not None else []
                part_results[p.index] = rows
            else:
                br.record_failure()
                self.batch_failures += 1
                if self.metrics is not None:
                    self.metrics.record("webhook_batch_failures_total", 1)
                part.note_dispatch("failed", p.device)
                host_parts.append(p)
        # host rung, scoped: only the degraded partitions' subsets, and
        # only the requests those subsets match
        errors: Dict[int, Exception] = {}
        degraded_reqs: Dict[int, List[int]] = {}
        for p in host_parts:
            try:
                fire("webhook.host_review")
            except FaultError as e:
                for i, hit in enumerate(masks[p.index]):
                    if hit:
                        errors.setdefault(i, EvaluationUnavailable(str(e)))
                part.note_dispatch("host", p.device)
                continue
            rows = [[] for _ in reviews]
            for i, review in enumerate(reviews):
                if not masks[p.index][i]:
                    continue
                degraded_reqs.setdefault(i, []).append(p.index)
                try:
                    responses = client.review_host(review, subset=p.subset)
                    resp = responses.by_target.get(self.target)
                    rows[i] = resp.results if resp is not None else []
                except Exception as e:
                    errors[i] = e
            part_results[p.index] = rows
            part.note_dispatch("host", p.device)
        self.batches_dispatched += 1
        self.requests_batched += len(batch)
        if self.metrics is not None:
            self.metrics.record("webhook_batches_total", 1)
            self.metrics.observe("webhook_batch_size", len(batch))
        self._record_spans(
            batch, wall0, t0,
            route="batched" if not host_parts else "partitioned",
        )
        if self.tracer is not None and degraded_reqs:
            # per-REQUEST degraded accounting: only requests whose
            # verdict was (partly) served from the host rung carry the
            # span — requests matching only healthy partitions show a
            # pure fused trace (the chaos e2e pins this)
            wall1 = wall0 + (time.perf_counter() - t0)
            for i, pidx in degraded_reqs.items():
                ctx = batch[i][2]
                if ctx is not None:
                    self.tracer.record_span(
                        "degraded_subset", wall0, wall1, parent=ctx,
                        plane=self.plane, partitions=sorted(pidx),
                    )
        # dispatch-explain facts (docs/observability.md §Decision log):
        # per-partition pruning-efficiency series — fused and host
        # partitions both evaluate only their mask-selected requests, a
        # mask-skipped partition nothing — plus the per-request
        # partition set and mask-derived rows
        host_idx = {p.index for p in host_parts}
        n_rev = len(reviews)
        key_count = {p.index: len(p.keys) for p in plan.partitions}
        corpus_rows = sum(key_count.values())
        touched = len(plan.partitions) - len(skipped_parts)
        note_touched = getattr(part, "note_batch_touched", None)
        if note_touched is not None:
            note_touched(touched, len(plan.partitions))
        for p, mask in zip(plan.partitions, masks):
            if p.index in skipped_parts:
                dispatched = 0
            else:
                dispatched = key_count[p.index] * len(
                    sel_by_part[p.index]
                )
            self._note_rows(
                p.index, dispatched, key_count[p.index] * n_rev
            )
        if self.decisions is not None:
            per_request: Dict[int, Dict[str, Any]] = {}
            for i in range(n_rev):
                matched = [
                    p.index
                    for p in plan.partitions
                    if masks[p.index][i]
                ]
                facts: Dict[str, Any] = {
                    "partitions_matched": matched,
                    "partitions_skipped": list(skipped_parts),
                    "partitions_touched": touched,
                    "rows_total": corpus_rows,
                    # provably-dead rows the corpus analyzer removed
                    # from the plan before dispatch ("why didn't this
                    # constraint fire" — docs/analysis.md)
                    "rows_excluded_static": len(
                        getattr(plan, "excluded_static", ()) or ()
                    ),
                    # the per-request rows pruned dispatch pays:
                    # constraint rows of the partitions this request's
                    # mask actually selects
                    "rows_dispatched": sum(
                        key_count[j] for j in matched
                    ),
                }
                if i in degraded_reqs:
                    facts["route"] = "degraded"
                    facts["partitions_degraded"] = sorted(
                        degraded_reqs[i]
                    )
                per_request[i] = facts
            self._note_decisions(
                batch, self._driver_route(n_rev),
                extdata_fetches=self._extdata_fetch_count() - fetch0,
                per_request=per_request,
                columns_skipped_static=(
                    self._liveness_skipped_count() - skip0
                ),
            )
        for i, (_, fut, ctx, _, _, _) in enumerate(batch):
            if i in errors:
                fut.set_exception(errors[i])
            else:
                merged = merge_partition_results(
                    [rows[i] for rows in part_results.values()],
                    plan.order,
                )
                fut.set_result(merged)
                self._note_integrity(
                    ctx, reviews[i], merged, route="partitioned",
                )
        part.run_probes(reviews)

    def _dispatch_host(self, batch, reviews, wall0: float, t0: float,
                       route: str) -> None:
        """The host-oracle rung of the degradation ladder: per-request
        INTERPRETER evaluation (`Client.review_host` — never a second
        device attempt). A request whose own host evaluation fails
        keeps its error (a poisoned request is still a 500); only when
        the host plane itself is down does the batch fall to the final
        rung — the typed EvaluationUnavailable that the handler answers
        with the endpoint's fail-open/fail-closed envelope."""
        try:
            fire("webhook.host_review")
        except FaultError as e:
            for _, fut, _, _, _, _ in batch:
                fut.set_exception(EvaluationUnavailable(str(e)))
            self._record_spans(batch, wall0, t0, route="unavailable")
            self._note_decisions(batch, "unavailable")
            return
        prefetch = getattr(self.client, "prefetch_external", None)
        if prefetch is not None:
            # a breaker-open batch never reached the fused path's
            # prefetch: dedupe + fetch the batch's external-data keys
            # once HERE so the per-request host evaluations below hit
            # the cache (one outbound fetch per provider per batch on
            # the degraded rung too)
            try:
                prefetch(reviews)
            except Exception:
                pass
        host = getattr(self.client, "review_host", None)
        if host is None:
            host = self.client.review
        fetch0 = self._extdata_fetch_count()
        for review, (_, fut, _, _, _, _) in zip(reviews, batch):
            try:
                responses = host(review)
                resp = responses.by_target.get(self.target)
                fut.set_result(resp.results if resp is not None else [])
            except Exception as e:
                fut.set_exception(e)
        self._record_spans(batch, wall0, t0, route=route)
        # host rung facts: every corpus row still evaluates, on the
        # interpreter — "degraded" when the breaker steered here,
        # "host" when a failed fused attempt fell back
        rows = self._corpus_rows()
        if rows is not None:
            self._note_rows("mono", rows * len(batch), rows * len(batch))
        self._note_decisions(
            batch, "degraded" if route == "degraded" else "host",
            rows_dispatched=rows, rows_total=rows,
            extdata_fetches=self._extdata_fetch_count() - fetch0,
        )

    def _record_spans(self, batch, wall0: float, t0: float, route: str) -> None:
        """Stamp this batch's shared timing window into every traced
        member request: queue_wait (submit -> dispatch start), dispatch
        (the fused evaluation), its flatten_encode / device_execute
        children from the driver's per-query phase split, and render.
        Phase offsets are synthesized sequentially inside the dispatch
        window — the driver reports durations, not wall stamps."""
        if self.tracer is None:
            return
        wall1 = wall0 + (time.perf_counter() - t0)
        drv = getattr(self.client, "_driver", None)
        stats = getattr(drv, "stats", None)
        phases: Dict[str, float] = {}
        attrs: Dict[str, Any] = {}
        if isinstance(stats, dict):
            phases = stats.get("phase_seconds") or {}
            for k in ("compiled_pairs", "interp_pairs", "n_results"):
                if k in stats:
                    attrs[k] = stats[k]
        render_s = phases.get("render", 0.0)
        for _, _, ctx, (sub_wall, _sub_perf), _, _ in batch:
            if ctx is None:
                continue
            self.tracer.record_span(
                "queue_wait", sub_wall, wall0, parent=ctx
            )
            d_ctx = self.tracer.record_span(
                "dispatch", wall0, wall1, parent=ctx,
                batch_size=len(batch), route=route, **attrs
            )
            cursor = wall0
            for phase in ("flatten_encode", "device_dispatch"):
                dt = phases.get(phase)
                if dt:
                    self.tracer.record_span(
                        phase, cursor, cursor + dt, parent=d_ctx
                    )
                    cursor += dt
            # always recorded: on the interpreter route rendering is
            # inlined in the evaluation, reported as a point span
            self.tracer.record_span(
                "render", wall1 - render_s, wall1, parent=d_ctx
            )


class BatchedValidationHandler(ValidationHandler):
    """ValidationHandler whose review path goes through the batcher."""

    def __init__(
        self,
        batcher: MicroBatcher,
        request_timeout: float = DEFAULT_REQUEST_TIMEOUT,
        **kwargs,
    ):
        super().__init__(
            batcher.client,
            batcher.target,
            namespace_getter=batcher.namespace_getter,
            **kwargs,
        )
        self.batcher = batcher
        self.request_timeout = request_timeout
        # per-thread deadline override: the framed ingest path stamps
        # the FRAME HEADER's budget here so the scheduler sees the
        # caller's real deadline instead of the server-side default
        self._deadline_local = threading.local()

    @contextmanager
    def deadline_scope(self, deadline: Optional[float]):
        """Pin _review calls on THIS thread to an absolute monotonic
        deadline (ingest frames carry one in the header). None is a
        no-op scope — the default request_timeout budget applies."""
        if deadline is None:
            yield
            return
        self._deadline_local.value = deadline
        try:
            yield
        finally:
            self._deadline_local.value = None

    def _review(
        self, request: Dict[str, Any], tracing: bool = False, span=None
    ) -> List[Any]:
        if tracing:
            # traced requests bypass the batcher: traces are per-request
            # by definition (the driver's batched path declines tracing)
            return super()._review(request, tracing=True, span=span)
        ctx = getattr(span, "context", None)
        # deadline propagation: the request's remaining budget rides to
        # the batch worker so expiry is checked BEFORE dispatch. Tenant
        # identity is extracted BEFORE enqueue too — shed verdicts must
        # carry it, and the scheduler's quotas key on it.
        override = getattr(self._deadline_local, "value", None)
        if override is not None:
            deadline = override
            budget = max(0.0, deadline - self.batcher._now())
        else:
            deadline = self.batcher._now() + self.request_timeout
            budget = self.request_timeout
        tenant = {
            "namespace": request.get("namespace", ""),
            "username": (request.get("userInfo") or {}).get(
                "username", ""
            ),
        }
        fut = self.batcher.submit(
            request, span_ctx=ctx, deadline=deadline, tenant=tenant
        )
        try:
            return fut.result(timeout=budget)
        except _FutureTimeout:
            # a hung dispatch (device stall): the caller gets the typed
            # unavailability — answered per fail policy — while the
            # worker finishes or dies in the background
            raise EvaluationTimeout(
                f"admission evaluation exceeded {budget:.3f}s"
            ) from None


class WebhookServer:
    """Stdlib HTTP(S) server: POST /v1/admit and /v1/admitlabel with
    AdmissionReview JSON bodies. `tls=True` terminates HTTPS with the
    rotating self-signed pair from `certs.CertRotator` (cert_dir
    defaults to a per-server temp dir)."""

    def __init__(
        self,
        client,
        target: str,
        port: int = 0,
        excluder=None,
        namespace_getter=None,
        exempt_namespaces=None,
        window_ms: float = 2.0,
        metrics=None,
        tls: bool = False,
        cert_dir: Optional[str] = None,
        # pre-built cert rotator (fleet.FleetCertRotator for the
        # Secret-backed shared store); None builds a pod-local
        # CertRotator in cert_dir. When the rotator exposes on_rotate
        # (the fleet one does), a rotation — our own OR a peer's —
        # re-loads the live SSL context so new handshakes serve the new
        # pair WITHOUT a restart.
        rotator=None,
        request_timeout: float = DEFAULT_REQUEST_TIMEOUT,
        trace_config=None,
        event_sink=None,
        emit_admission_events: bool = False,
        log_denies: bool = False,
        logger=None,
        tracer=None,
        # mutation.MutationSystem: wires the /v1/mutate plane (None =
        # endpoint returns 404, validation-only pod)
        mutation_system=None,
        # overload / degradation envelope (docs/robustness.md):
        # fail_policy is what a shed/expired/unevaluable request gets —
        # "open" (allow; the reference's failurePolicy: Ignore posture)
        # or "closed" (deny 503); max_queue bounds the admission queue
        fail_policy: str = "open",
        max_queue: Optional[int] = DEFAULT_MAX_QUEUE,
        # "127.0.0.1" keeps tests hermetic; in-cluster serving must bind
        # the pod IP surface ("0.0.0.0" via run.py) or the apiserver and
        # kubelet probes can never connect
        bind_addr: str = "127.0.0.1",
        # agent-action plane (docs/targets.md): True wires
        # POST /v1/agent/review over the client's registered
        # AgentActionTarget; agent_mutation_system additionally screens
        # and rewrites tool-call arguments before validation
        agent_review: bool = False,
        agent_mutation_system=None,
        # graceful drain (docs/robustness.md): seconds stop() holds the
        # listener OPEN after flipping readiness, so a load balancer
        # watching /readyz routes away before connections start failing
        # (the preStop-sleep pattern; 0 = flip-and-close immediately)
        drain_grace_s: float = 0.0,
        # device fault domains (docs/robustness.md §Fault domains):
        # split the constraint corpus into this many partitions, each
        # on its own logical device with its own breaker — a sick
        # device sheds only its constraint subset, not the plane.
        # 0/None keeps the monolithic dispatch + single plane breaker.
        partitions: Optional[int] = None,
        partition_devices: Optional[int] = None,
        # obs.FlightRecorder: threaded to the batchers (shed bursts),
        # the plane breaker, and the partitioner's per-device breakers
        # so a trip anywhere on this server captures one postmortem
        recorder=None,
        # obs.DecisionLog: per-admission "why" records across every
        # plane this server mounts (validation / mutation / agent);
        # None = decision plane off (docs/observability.md §Decision
        # log; bench_webhook --attribution measures the on/off delta)
        decision_log=None,
        # obs.CostAttributor: measured per-constraint device seconds
        # feed the partition planner (cost/locality-guided plan builds
        # instead of round-robin); replica tags /debug/partitions the
        # way /debug/costs is tagged
        attributor=None,
        replica: Optional[str] = None,
        # analysis.corpus.CorpusPlane: feeds the partition planner its
        # provably-dead (verdict-safe prunable) constraint keys
        corpus=None,
        # admission scheduling (docs/operations.md §Admission
        # scheduling): policy for every plane's batcher — "fifo" is the
        # bit-compatible rollback path, "deadline" enables EDF batch
        # formation + predictive shedding + fair-share quotas. slo is
        # the obs.SloEngine feeding the overload/saturation loop.
        sched_policy: str = "fifo",
        slo=None,
        # integrity.IntegrityPlane (docs/robustness.md §Verdict
        # integrity): shadow-oracle sampling on the validation batcher
        # + corruption-quarantine wiring to the partitioner
        integrity=None,
        # wire-speed ingest plane (docs/ingest.md): True mounts a
        # framed-stream listener (ingest.IngestServer) next to the
        # legacy HTTP port — persistent multiplexed connections,
        # zero-copy AdmissionReview decode, frame-header deadlines.
        # Rollback is ingest=False (--ingest off): the HTTP path is
        # untouched either way.
        ingest: bool = False,
        ingest_port: int = 0,
        ingest_decode: str = "zerocopy",
        ingest_max_inflight: int = 256,
        ingest_workers: int = 64,
    ):
        self.client = client  # warmup() compiles through it
        self.tracer = tracer
        self.recorder = recorder
        self.decision_log = decision_log
        self.request_timeout = request_timeout
        self.drain_grace_s = drain_grace_s
        self.partitioner = None
        if partitions:
            from ..parallel.partition import PartitionDispatcher

            self.partitioner = PartitionDispatcher(
                client,
                target,
                k=partitions,
                devices=partition_devices,
                plane="validation",
                metrics=metrics,
                tracer=tracer,
                recorder=recorder,
                attributor=attributor,
                replica=replica,
                corpus=corpus,
            )
        # graceful-drain state: `draining` flips BEFORE the listener
        # closes (readiness consults it), in-flight HTTP requests are
        # counted so stop() can wait for them, and on_drain callbacks
        # let the control plane (Runner readyz, a soak harness's LB
        # model) observe the flip at its exact ordering point
        self.draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._drain_callbacks: List[Callable[[], None]] = []
        self.batcher = MicroBatcher(
            client, target, window_ms=window_ms,
            namespace_getter=namespace_getter,
            metrics=metrics, tracer=tracer,
            max_queue=max_queue,
            partitioner=self.partitioner,
            recorder=recorder,
            decisions=decision_log,
            sched_policy=sched_policy,
            slo=slo,
            attributor=attributor,
            integrity=integrity,
        )
        self.integrity = integrity
        if integrity is not None:
            # the mismatch ledger needs the dispatcher to trip
            # corruption quarantine; the shadow oracle re-evaluates
            # through the serving client's host rung
            try:
                integrity.attach_client(client)
                if self.partitioner is not None:
                    integrity.attach_dispatcher(self.partitioner)
            except Exception:
                pass
        self.mutate_batcher = None
        self.mutation_handler = None
        if mutation_system is not None:
            # local import: mutate.py imports from this module
            from .mutate import MutateBatcher, MutationHandler

            self.mutate_batcher = MutateBatcher(
                mutation_system, window_ms=window_ms,
                namespace_getter=namespace_getter,
                metrics=metrics, tracer=tracer,
                max_queue=max_queue,
                decisions=decision_log,
                sched_policy=sched_policy,
                slo=slo,
                attributor=attributor,
            )
            self.mutation_handler = MutationHandler(
                self.mutate_batcher,
                excluder=excluder,
                metrics=metrics,
                request_timeout=request_timeout,
                logger=logger,
                tracer=tracer,
                fail_policy=fail_policy,
                decision_log=decision_log,
            )
        self.handler = BatchedValidationHandler(
            self.batcher, excluder=excluder, metrics=metrics,
            request_timeout=request_timeout,
            trace_config=trace_config,
            event_sink=event_sink,
            emit_admission_events=emit_admission_events,
            log_denies=log_denies,
            logger=logger,
            tracer=tracer,
            fail_policy=fail_policy,
            decision_log=decision_log,
        )
        self.label_handler = NamespaceLabelHandler(exempt_namespaces)
        self.agent_batcher = None
        self.agent_mutate_batcher = None
        self.agent_handler = None
        if agent_review:
            from ..agentaction import make_agent_plane

            (
                self.agent_batcher,
                self.agent_mutate_batcher,
                self.agent_handler,
            ) = make_agent_plane(
                client,
                window_ms=window_ms,
                mutation_system=agent_mutation_system,
                metrics=metrics,
                tracer=tracer,
                logger=logger,
                fail_policy=fail_policy,
                request_timeout=request_timeout,
                max_queue=max_queue,
                decision_log=decision_log,
                sched_policy=sched_policy,
                slo=slo,
                attributor=attributor,
            )
        self.ingest = None
        if ingest:
            # local import: ingest.server imports review_envelope back
            # from this module
            from ..ingest.server import IngestServer

            self.ingest = IngestServer(
                self,
                host=bind_addr,
                port=ingest_port,
                decode=ingest_decode,
                max_inflight=ingest_max_inflight,
                workers=ingest_workers,
                metrics=metrics,
                tracer=tracer,
                decision_log=decision_log,
            )
            self.ingest_port = self.ingest.port
        outer = self

        class _Handled(Exception):
            """Control flow: response already written by the branch."""

        class _Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: every response already carries an
            # explicit Content-Length, so persistent connections are
            # safe — sequential admissions from one client reuse a
            # single socket instead of paying setup per request
            # (docs/ingest.md §Keep-alive). Chunked bodies are not
            # produced or accepted.
            protocol_version = "HTTP/1.1"

            def do_POST(self):  # noqa: N802
                # in-flight accounting: an ACCEPTED request must finish
                # even when stop() runs concurrently — the drain waits
                # on this counter before tearing the batchers down
                with outer._inflight_cv:
                    outer._inflight += 1
                try:
                    self._do_post()
                finally:
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        outer._inflight_cv.notify_all()

            def _do_post(self):
                from ..obs import (
                    derive_trace_id,
                    format_traceparent,
                    parse_traceparent,
                )

                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                trace_id = None
                try:
                    review = json.loads(body)
                    request = review.get("request") or {}
                    # W3C trace propagation (docs/observability.md):
                    # an inbound `traceparent` names the request's
                    # trace; without one the admission UID derives a
                    # deterministic id — either way the id rides the
                    # handler's root span, the denial log, the response
                    # envelope, and /debug/traces?trace_id=
                    trace_id = parse_traceparent(
                        self.headers.get("traceparent")
                    ) or derive_trace_id(request.get("uid"))
                    if (
                        outer.decision_log is not None
                        and trace_id is not None
                    ):
                        # front-door attribution (docs/ingest.md): the
                        # decision record names which decode route
                        # served this admission and what it weighed
                        outer.decision_log.note_dispatch(
                            trace_id,
                            decode_route="legacy",
                            bytes_on_wire=length,
                        )
                    if self.path == "/v1/admitlabel":
                        resp = outer.label_handler.handle(request)
                    elif self.path == "/v1/mutate":
                        if outer.mutation_handler is None:
                            payload = json.dumps(
                                {"error": "mutation not enabled"}
                            ).encode()
                            self.send_response(404)
                            raise _Handled()
                        resp = outer.mutation_handler.handle(
                            request, trace_id=trace_id
                        )
                    elif self.path == "/v1/agent/review":
                        if outer.agent_handler is None:
                            payload = json.dumps(
                                {"error": "agent review not enabled"}
                            ).encode()
                            self.send_response(404)
                            raise _Handled()
                        resp = outer.agent_handler.handle(
                            request, trace_id=trace_id
                        )
                    else:
                        resp = outer.handler.handle(
                            request, trace_id=trace_id
                        )
                    payload = json.dumps(
                        review_envelope(
                            review, request, resp, trace_id=trace_id
                        )
                    ).encode()
                    self.send_response(200)
                except _Handled:
                    pass
                except Exception as e:
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                try:
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    if trace_id is not None:
                        self.send_header(
                            "traceparent", format_traceparent(trace_id)
                        )
                    self.end_headers()
                    self.wfile.write(payload)
                except (BrokenPipeError, ConnectionResetError):
                    # client gave up (its own timeout) mid-response —
                    # nothing to salvage, and the handler thread must
                    # not die noisily
                    pass

            def log_message(self, *args):  # silence default stderr spam
                pass

        class _Server(ThreadingHTTPServer):
            # the stdlib default backlog (5) resets bursts of
            # concurrent connections — exactly the micro-batching
            # workload; deep enough for a full batch window
            request_queue_size = 512
            daemon_threads = True

        self._httpd = _Server((bind_addr, port), _Handler)
        self.rotator = rotator
        self._ssl_ctx = None
        if tls:
            import ssl
            import tempfile

            from .certs import CertRotator

            if self.rotator is None:
                if cert_dir is None:
                    cert_dir = tempfile.mkdtemp(prefix="gk-certs-")
                self.rotator = CertRotator(cert_dir)
            cert_path, key_path = self.rotator.ensure()  # CertsMounted gate
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert_path, key_path)
            self._ssl_ctx = ctx
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )
            # rotation pickup without restart: SSLContext is live — a
            # re-load swaps the pair for every handshake AFTER this
            # point while established connections finish on the old one
            on_rotate = getattr(self.rotator, "on_rotate", None)
            if on_rotate is not None:
                on_rotate(self._reload_tls)
        self.scheme = "https" if tls else "http"
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self.warm = False

    def _reload_tls(self) -> None:
        if self._ssl_ctx is None or self.rotator is None:
            return
        try:
            self._ssl_ctx.load_cert_chain(
                self.rotator.cert_path, self.rotator.key_path
            )
        except Exception:
            # a torn read is impossible (atomic-rename installs), but a
            # rotation racing deletion must not kill serving — the old
            # pair keeps serving until the next successful reload
            pass

    def start(self) -> None:
        self.batcher.start()
        if self.mutate_batcher is not None:
            self.mutate_batcher.start()
        if self.agent_batcher is not None:
            self.agent_batcher.start()
        if self.agent_mutate_batcher is not None:
            self.agent_mutate_batcher.start()
        if self.ingest is not None:
            self.ingest.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def warmup(self, sample_objects=None) -> float:
        """Pre-compile the fused review path for common batch shapes so
        the first real admission request doesn't pay the jit compile
        inside its deadline (first compile is tens of seconds on TPU;
        the reference has no analog — its interpreter has no compile
        step, but it DOES gate Ready on state ingestion; compile warmth
        is this engine's equivalent). Returns seconds spent."""
        from ..constraint.handler import handler_for

        t0 = time.monotonic()
        handler = handler_for(self.client, self.batcher.target)
        if sample_objects is None:
            # the target supplies shape-covering synthetic requests
            requests = handler.sample_requests(192)
        else:
            requests = sample_objects
        reviews = [handler.augment_request(r) for r in requests]
        # device-sized batches covering the common occupancy buckets
        # (row counts bucket at 64/128/256; sub-device-threshold batches
        # route to the interpreter and need no compile).
        # warm_review_path compiles WITHOUT holding the driver's serving
        # mutex, so admission keeps flowing on the interpreter route
        # until the compiled route swaps in (serve-while-compiling).
        # The attribute/callable resolution stays OUTSIDE the try: a
        # silently-swallowed AttributeError here turned the whole warmup
        # into a no-op for a full round; only the compile itself is
        # best-effort.
        warm = self.client.warm_review_path
        for batch in (reviews[:16], reviews[:100], reviews):
            try:
                warm(batch)
            except Exception:
                pass  # warmup is best-effort; serving works unwarmed
        self.warm = True
        return time.monotonic() - t0

    def sched_snapshot(self) -> Dict[str, Any]:
        """Per-plane admission-scheduler state: the `/readyz`
        `stats.sched` and `/debug/sched` document (docs/operations.md
        §Admission scheduling)."""
        out: Dict[str, Any] = {"validation": self.batcher.sched.snapshot()}
        if self.mutate_batcher is not None:
            out["mutation"] = self.mutate_batcher.sched.snapshot()
        if self.agent_batcher is not None:
            out["agent"] = self.agent_batcher.sched.snapshot()
        if self.agent_mutate_batcher is not None:
            out["agent_mutation"] = (
                self.agent_mutate_batcher.sched.snapshot()
            )
        return out

    # -- graceful drain (docs/robustness.md) ---------------------------------

    @property
    def ready(self) -> bool:
        """Serving readiness: False from the instant a drain begins —
        the signal a load balancer / kubelet readiness probe needs
        BEFORE the listener goes away."""
        return not self.draining

    def on_drain(self, callback: Callable[[], None]) -> None:
        """Register a callback fired when draining flips (before the
        listener closes). Used by the Runner's readyz plane and by
        harnesses modeling a load balancer."""
        self._drain_callbacks.append(callback)

    def begin_drain(self) -> None:
        """Flip not-ready. Idempotent; does NOT close anything — the
        listener keeps accepting (and the batchers keep evaluating)
        until stop() proceeds, so a request racing the flip still gets
        a real answer instead of a reset."""
        if self.draining:
            return
        self.draining = True
        for cb in list(self._drain_callbacks):
            try:
                cb()
            except Exception:
                pass  # observers must not be able to wedge the drain

    def _await_inflight(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(timeout=min(remaining, 0.1))
        return True

    def stop(self, drain_grace_s: Optional[float] = None) -> None:
        """Graceful shutdown, in the only order that sheds zero
        accepted requests: (1) readiness flips not-ready, (2) the
        drain grace lets the LB react while the listener still
        accepts, (3) the listener closes, (4) in-flight requests —
        which block on batch futures — complete because the batchers
        are STILL RUNNING, and only then (5) the batchers stop
        (dispatching any leftovers inline) and the socket is released.
        A SIGTERM mid-load therefore answers everything it accepted."""
        grace = self.drain_grace_s if drain_grace_s is None else drain_grace_s
        self.begin_drain()
        if grace > 0:
            time.sleep(grace)
        self._httpd.shutdown()
        if self.ingest is not None:
            # stop NEW frames; accepted ones are in _inflight below
            self.ingest.stop_accepting()
        # bounded by the request envelope: no accepted request can
        # legitimately outlive its own timeout + a dispatch window
        self._await_inflight(min(self.request_timeout + 1.0, 15.0))
        if self.ingest is not None:
            self.ingest.close()
        self.batcher.stop()
        if self.mutate_batcher is not None:
            self.mutate_batcher.stop()
        if self.agent_batcher is not None:
            self.agent_batcher.stop()
        if self.agent_mutate_batcher is not None:
            self.agent_mutate_batcher.stop()
        if self.partitioner is not None:
            self.partitioner.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # release the listening socket fd: a soak that restarts
        # replicas repeatedly must not leak one fd per lifecycle
        try:
            self._httpd.server_close()
        except Exception:
            pass
