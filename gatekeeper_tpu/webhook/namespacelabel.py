"""Namespace-label guard webhook.

Mirrors pkg/webhook/namespacelabel.go: rejects adding the
`admission.gatekeeper.sh/ignore` label to a Namespace unless the
namespace is in the exempt set (--exempt-namespace flag,
namespacelabel.go:25-28,69-90).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Set

from .policy import AdmissionResponse

IGNORE_LABEL = "admission.gatekeeper.sh/ignore"


class NamespaceLabelHandler:
    def __init__(self, exempt_namespaces: Optional[Iterable[str]] = None):
        self.exempt: Set[str] = set(exempt_namespaces or [])

    def handle(self, request: Dict[str, Any]) -> AdmissionResponse:
        kind = request.get("kind") or {}
        if kind.get("kind") != "Namespace" or kind.get("group"):
            return AdmissionResponse(True, "")
        obj = request.get("object") or {}
        labels = ((obj.get("metadata") or {}).get("labels")) or {}
        if IGNORE_LABEL not in labels:
            return AdmissionResponse(True, "")
        name = (obj.get("metadata") or {}).get("name") or request.get(
            "name", ""
        )
        if name in self.exempt:
            return AdmissionResponse(True, "")
        return AdmissionResponse(
            False,
            f"only exempt namespaces can have the {IGNORE_LABEL} label",
            code=403,
        )
