"""The `/v1/mutate` serving path: micro-batched, kernel-screened.

`MutateBatcher` rides `MicroBatcher`'s coalescing worker loop (same
window/max_batch/submit semantics as the validation plane) but its
dispatch is the mutation pipeline:

  1. **screen** — ONE `match_matrix` device call for the whole batch
     decides which mutators apply to which requests (mutator Match
     specs reuse the constraint match encoding end-to-end);
  2. **apply** — CPU fixpoint application for screened-in pairs only
     (`MutationSystem.apply`; ConvergenceError fails THAT request, the
     object is never admitted non-converged);
  3. **render** — RFC 6902 JSONPatch diff per request.

Each traced request gets queue_wait / screen_dispatch / apply_fixpoint
/ render_patch spans stamped by the batch worker (the PR-2 span
conventions), and the Prometheus series in docs/metrics.md §Mutation
account the same pipeline.

`MutationHandler` is the policy layer: service-account bypass, excluded
namespaces, operation filtering, metrics, and the AdmissionResponse
with the base64 JSONPatch payload.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Dict, List, Optional, Tuple

from ..faults import (
    AdmissionUnavailable,
    EvaluationTimeout,
    EvaluationUnavailable,
    fire,
)
from ..mutation import ConvergenceError, MutationApplyError, json_patch
from .policy import (
    SERVICE_ACCOUNT,
    AdmissionResponse,
    note_unavailable_decision,
    unavailable_response,
)
from .server import DEFAULT_MAX_QUEUE, DEFAULT_REQUEST_TIMEOUT, MicroBatcher

# mutators act on the incoming object; DELETE carries none
_MUTATE_OPERATIONS = ("CREATE", "UPDATE", "")


class MutateBatcher(MicroBatcher):
    """MicroBatcher whose fused dispatch is screen→apply→render over a
    MutationSystem instead of Client.review_many. Inherits the full
    overload/degradation envelope (bounded queue, deadline shedding,
    device circuit breaker) with the mutate-plane specifics: the
    breaker gates the DEVICE SCREEN, the host-oracle rung is
    `MutationSystem.screen_host`, and convergence failures are never
    softened by the envelope — an unconverged object is rejected no
    matter what state the ladder is in."""

    # plane tag on shed/breaker/queue metrics (docs/robustness.md)
    plane = "mutation"

    def __init__(
        self,
        system,
        window_ms: float = 2.0,
        max_batch: int = 256,
        namespace_getter=None,
        metrics=None,
        tracer=None,
        max_queue: Optional[int] = DEFAULT_MAX_QUEUE,
        breaker=None,
        decisions=None,
        sched_policy: str = "fifo",
        slo=None,
        attributor=None,
    ):
        super().__init__(
            client=None,
            target="mutation",
            window_ms=window_ms,
            max_batch=max_batch,
            namespace_getter=namespace_getter,
            metrics=metrics,
            tracer=tracer,
            max_queue=max_queue,
            breaker=breaker,
            decisions=decisions,
            sched_policy=sched_policy,
            slo=slo,
            attributor=attributor,
        )
        self.system = system

    # -- the mutate dispatch -------------------------------------------------

    def _dispatch(self, batch: List[Tuple]):
        batch = self._strip_expired(batch)
        if not batch:
            return
        wall0, t0 = time.time(), time.perf_counter()
        reviews = []
        for request, _, _, _, _, _ in batch:
            review = dict(request)
            ns_obj = None
            namespace = request.get("namespace", "")
            if namespace and self.namespace_getter is not None:
                ns_obj = self.namespace_getter(namespace)
            if ns_obj is not None:
                review["_unstable"] = {"namespace": ns_obj}
            reviews.append(review)

        t_scr = time.perf_counter()
        breaker = self.breaker
        muts = matrix = None
        route = "batched"
        if breaker is not None and not breaker.allow():
            # breaker open: the device screen has been failing — go
            # straight to the host-oracle screen, paying zero doomed
            # device attempts for this batch
            if self.metrics is not None:
                self.metrics.record(
                    "webhook_degraded_dispatch_total", 1, plane=self.plane
                )
            route = "degraded"
        else:
            try:
                fire("mutate.screen_dispatch")
                muts, matrix = self.system.screen(reviews)
                if breaker is not None:
                    breaker.record_success()
            except Exception:
                # device-screen failure degrades to the host oracle —
                # the mutation plane keeps answering (fail-open on the
                # SCREEN, never on convergence)
                if breaker is not None:
                    breaker.record_failure()
                self.batch_failures += 1
                if self.metrics is not None:
                    self.metrics.record("mutation_batch_failures_total", 1)
                route = "fallback"
        if muts is None:
            try:
                fire("mutate.host_screen")
                muts, matrix = self.system.screen_host(reviews)
            except Exception as e:
                # every rung down: the typed unavailability the handler
                # answers with the endpoint's fail policy (the apiserver
                # would admit unmutated on webhook failure too — here it
                # is explicit and counted). NEVER a half-screened batch.
                for _, fut, ctx, (sub_wall, _sp), _, _ in batch:
                    fut.set_exception(EvaluationUnavailable(str(e)))
                    self._record_mutate_spans(
                        ctx, sub_wall, wall0, wall0, 0.0, 0.0, 0.0,
                        len(batch), 0, "unavailable",
                    )
                self._note_decisions(batch, "unavailable")
                return
        screen_s = time.perf_counter() - t_scr

        self.batches_dispatched += 1
        self.requests_batched += len(batch)
        if self.metrics is not None:
            self.metrics.record("mutation_batches_total", 1)
            self.metrics.observe("mutation_screen_batch_size", len(batch))

        wall_scr_end = wall0 + (time.perf_counter() - t0)
        for i, (
            (request, fut, ctx, (sub_wall, _), _dl, _tk), review
        ) in enumerate(zip(batch, reviews)):
            selected = [m for j, m in enumerate(muts) if matrix[j, i]]
            obj = review.get("object")
            apply_s = render_s = 0.0
            iters = 0
            try:
                if not isinstance(obj, dict) or not selected:
                    patch: List[Dict[str, Any]] = []
                else:
                    t_a = time.perf_counter()
                    mutated, iters = self.system.apply(
                        obj, review, selected
                    )
                    apply_s = time.perf_counter() - t_a
                    t_r = time.perf_counter()
                    patch = json_patch(obj, mutated)
                    render_s = time.perf_counter() - t_r
                if self.metrics is not None:
                    if iters:
                        self.metrics.observe(
                            "mutation_fixpoint_iterations", iters
                        )
                    if patch:
                        self.metrics.observe(
                            "mutation_patch_bytes",
                            len(json.dumps(patch)),
                        )
                fut.set_result(patch)
            except (ConvergenceError, MutationApplyError) as e:
                if self.metrics is not None and isinstance(
                    e, ConvergenceError
                ):
                    self.metrics.record("mutation_divergence_total", 1)
                fut.set_exception(e)
            except Exception as e:
                fut.set_exception(e)
            self._record_mutate_spans(
                ctx, sub_wall, wall0, wall_scr_end, screen_s,
                apply_s, render_s, len(batch), len(selected), route,
            )
            if self.decisions is not None:
                tid = getattr(ctx, "trace_id", None)
                if tid is not None:
                    # the mutate plane's "why": which route screened
                    # the batch, how many mutators matched, and the
                    # fixpoint iteration count (a 15-iteration record
                    # is one churn away from a divergence 500)
                    self.decisions.note_dispatch(
                        tid,
                        route={
                            "batched": "fused",
                            "fallback": "host",
                        }.get(route, route),
                        mutators_matched=len(selected),
                        fixpoint_iterations=iters,
                        batch_size=len(batch),
                    )
        if muts:
            # mutation-plane pruning series: every screened (mutator ×
            # request) row is dispatched today — the same instrument
            # item 1's pruned dispatch will move for validation
            self._note_rows(
                "mono", len(muts) * len(batch), len(muts) * len(batch)
            )

    def _record_mutate_spans(
        self, ctx, sub_wall, wall0, wall_scr_end, screen_s,
        apply_s, render_s, batch_size, n_mutators, route,
    ) -> None:
        """Span taxonomy for the mutate plane: queue_wait (submit →
        dispatch), screen_dispatch (the shared kernel screen, recorded
        into every member trace), then per-request apply_fixpoint and
        render_patch laid out sequentially after the screen window."""
        if self.tracer is None or ctx is None:
            return
        self.tracer.record_span("queue_wait", sub_wall, wall0, parent=ctx)
        self.tracer.record_span(
            "screen_dispatch", wall0, wall0 + screen_s, parent=ctx,
            batch_size=batch_size, route=route,
        )
        cursor = wall_scr_end
        self.tracer.record_span(
            "apply_fixpoint", cursor, cursor + apply_s, parent=ctx,
            mutators=n_mutators,
        )
        cursor += apply_s
        self.tracer.record_span(
            "render_patch", cursor, cursor + render_s, parent=ctx
        )


class MutationHandler:
    """Mutating-admission policy layer over the batcher (the mutation
    webhook's counterpart of ValidationHandler)."""

    def __init__(
        self,
        batcher: MutateBatcher,
        excluder=None,
        metrics=None,
        request_timeout: float = DEFAULT_REQUEST_TIMEOUT,
        logger=None,
        tracer=None,
        # same envelope semantics as ValidationHandler: what a request
        # that could NOT be screened/applied (shed, expired, every rung
        # down) gets. Convergence failures stay 500 regardless — an
        # unconverged object is NEVER admitted.
        fail_policy: str = "open",
        # obs.DecisionLog (docs/observability.md §Decision log)
        decision_log=None,
    ):
        from ..logs import null_logger

        if fail_policy not in ("open", "closed"):
            raise ValueError(
                f"fail_policy must be 'open' or 'closed', got {fail_policy!r}"
            )
        self.fail_policy = fail_policy
        self.decision_log = decision_log
        self.batcher = batcher
        self.excluder = excluder
        self.metrics = metrics
        self.request_timeout = request_timeout
        self.log = logger if logger is not None else null_logger()
        self.tracer = tracer

    def handle(
        self, request: Dict[str, Any], trace_id: Optional[str] = None
    ) -> AdmissionResponse:
        from ..obs import start_span

        t0 = time.perf_counter()
        kind = request.get("kind") or {}
        with start_span(
            self.tracer,
            "mutate_handler",
            trace_id=trace_id,
            resource_kind=kind.get("kind", ""),
            resource_namespace=request.get("namespace", ""),
            resource_name=request.get("name", ""),
            operation=request.get("operation", ""),
        ) as span:
            # shed/unavailable outcomes override the verdict below —
            # a fail-open shed must NOT be recorded as a healthy allow
            # (per-tenant shed accounting reads these records)
            decision: Dict[str, Any] = {}
            resp = self._handle(request, span, decision)
            span.set_attr(
                mutation_status=(
                    "error"
                    if not resp.allowed
                    else ("mutated" if resp.patch else "unchanged")
                ),
                code=resp.code,
            )
        status = (
            "error"
            if not resp.allowed
            else ("mutated" if resp.patch else "unchanged")
        )
        duration_s = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.record(
                "mutation_request_count", 1, mutation_status=status
            )
            self.metrics.observe(
                "mutation_request_duration_seconds",
                duration_s,
                exemplar=getattr(span, "trace_id", None),
                mutation_status=status,
            )
        if self.decision_log is not None:
            verdict = decision.pop("verdict", None) or (
                "allow" if resp.allowed else "error"
            )
            self.decision_log.record_decision(
                "mutation",
                verdict,
                code=resp.code,
                trace_id=getattr(span, "trace_id", None) or trace_id,
                duration_ms=duration_s * 1e3,
                tenant={
                    "namespace": request.get("namespace", ""),
                    "username": (request.get("userInfo") or {}).get(
                        "username", ""
                    ),
                },
                message=resp.message if not resp.allowed else "",
                deadline_slack_ms=(
                    (self.request_timeout - duration_s) * 1e3
                ),
                mutation_status=status,
                patch_ops=len(resp.patch or []),
                **decision,
            )
        return resp

    def _handle(
        self, request: Dict[str, Any], span=None, decision=None
    ) -> AdmissionResponse:
        from ..control import PROCESS_WEBHOOK

        user = (request.get("userInfo") or {}).get("username", "")
        if user == SERVICE_ACCOUNT:
            return AdmissionResponse(True, "Gatekeeper does not self-manage")
        if request.get("operation", "") not in _MUTATE_OPERATIONS:
            return AdmissionResponse(True, "")
        namespace = request.get("namespace", "")
        if (
            namespace
            and self.excluder is not None
            and self.excluder.is_namespace_excluded(
                PROCESS_WEBHOOK, namespace
            )
        ):
            return AdmissionResponse(
                True, "Namespace is set to be ignored by Gatekeeper config"
            )
        # deadline propagation: the request's remaining budget rides to
        # the batch worker so expiry is checked BEFORE the screen; the
        # tenant identity rides too (extracted BEFORE enqueue so shed
        # accounting and fair-share quotas key on it)
        deadline = self.batcher._now() + self.request_timeout
        fut = self.batcher.submit(
            request, span_ctx=getattr(span, "context", None),
            deadline=deadline,
            tenant={"namespace": namespace, "username": user},
        )
        try:
            try:
                patch = fut.result(timeout=self.request_timeout)
            except _FutureTimeout:
                raise EvaluationTimeout(
                    f"mutation evaluation exceeded {self.request_timeout}s"
                ) from None
        except (ConvergenceError, MutationApplyError) as e:
            # NEVER admit a non-converged / half-mutable object — this
            # stays a hard 500 even under fail-open (the envelope covers
            # requests that were never evaluated, not poisoned ones)
            self.log.error(
                "mutation failed",
                process="mutation",
                err=e,
                resource_name=request.get("name", ""),
                resource_namespace=namespace,
            )
            return AdmissionResponse(False, str(e), code=500)
        except AdmissionUnavailable as e:
            # shed / expired / every screen rung down: the fail-policy
            # envelope (fail-open admits UNMUTATED — exactly what the
            # apiserver's failurePolicy: Ignore would do on timeout)
            if decision is not None:
                note_unavailable_decision(decision, e)
            return unavailable_response(
                e, fail_policy=self.fail_policy, metrics=self.metrics,
                log=self.log, span=span, plane="mutation",
            )
        except Exception as e:
            return AdmissionResponse(False, str(e), code=500)
        return AdmissionResponse(True, "", patch=patch or None)
