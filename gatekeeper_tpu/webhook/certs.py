"""Webhook TLS: self-signed CA + server certificate with rotation.

Behavioral mirror of pkg/webhook/certs.go:
  * a self-signed CA valid 10 years (createCACert, certs.go:265-301)
    signs a server certificate valid 1 year (createCertPEM,
    certs.go:303-344);
  * certificates are refreshed when missing, invalid, or within the
    90-day rotation lookahead of expiry (refreshCertIfNeeded +
    lookaheadInterval, certs.go:119-181,346);
  * artifacts live in a directory as ca.crt / tls.crt / tls.key (the
    reference stores them in a Secret mounted at certDir); serving
    blocks until they exist (main.go:154-172's CertsMounted gate is the
    `ensure()` call here).

The CA bundle injection into a ValidatingWebhookConfiguration
(certs.go:183-263) maps to `ca_bundle()` — the control plane hands it
to whatever registers the webhook.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import threading
from typing import Optional, Tuple

CA_VALIDITY_DAYS = 3650  # 10 years (certs.go:269)
CERT_VALIDITY_DAYS = 365  # 1 year (certs.go:307)
LOOKAHEAD_DAYS = 90  # rotation lookahead (certs.go:346)

CA_NAME = "gatekeeper-ca"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class CertRotator:
    """Generates and rotates the CA + server cert pair on disk."""

    def __init__(
        self,
        cert_dir: str,
        dns_name: str = "localhost",
        now=None,
    ):
        self.cert_dir = cert_dir
        self.dns_name = dns_name
        self._now = now if now is not None else _now
        self._lock = threading.Lock()
        self.rotations = 0

    # -- paths ---------------------------------------------------------------

    @property
    def ca_path(self) -> str:
        return os.path.join(self.cert_dir, "ca.crt")

    @property
    def cert_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.crt")

    @property
    def key_path(self) -> str:
        return os.path.join(self.cert_dir, "tls.key")

    # -- public --------------------------------------------------------------

    def ensure(self) -> Tuple[str, str]:
        """Refresh-if-needed; returns (cert_path, key_path). The serving
        layer calls this before binding TLS (the CertsMounted gate)."""
        with self._lock:
            if self._needs_refresh():
                self._refresh()
        return self.cert_path, self.key_path

    def ca_bundle(self) -> bytes:
        self.ensure()
        with open(self.ca_path, "rb") as f:
            return f.read()

    # -- internals -----------------------------------------------------------

    def _needs_refresh(self) -> bool:
        for p in (self.ca_path, self.cert_path, self.key_path):
            if not os.path.exists(p):
                return True
        exp = self._cert_expiry(self.cert_path)
        if exp is None:
            return True
        lookahead = self._now() + datetime.timedelta(days=LOOKAHEAD_DAYS)
        return exp <= lookahead

    @staticmethod
    def pem_expiry(data: bytes) -> Optional[datetime.datetime]:
        """not_valid_after of the first certificate in a PEM blob, or
        None when unparseable (treated as needs-refresh). Prefers the
        `cryptography` package; containers without it (the bench image)
        fall back to the `openssl` binary."""
        try:
            from cryptography import x509

            cert = x509.load_pem_x509_certificate(data)
            return cert.not_valid_after_utc
        except ImportError:
            return _openssl_expiry(data)
        except Exception:
            return None

    @classmethod
    def _cert_expiry(cls, path: str) -> Optional[datetime.datetime]:
        try:
            with open(path, "rb") as f:
                return cls.pem_expiry(f.read())
        except Exception:
            return None

    def _refresh(self) -> None:
        self.install_artifacts(self.generate_pair())
        self.rotations += 1

    def generate_pair(self) -> dict:
        """Generate a fresh CA + server pair; returns the PEM artifacts
        as {"ca.crt": bytes, "tls.crt": bytes, "tls.key": bytes} WITHOUT
        touching disk — the fleet cert store offers this dict to the
        shared Secret before any replica serves it. Prefers the
        `cryptography` package; falls back to the `openssl` binary so
        TLS serving works in containers without the wheel."""
        try:
            from cryptography import x509  # noqa: F401
        except ImportError:
            return _openssl_generate_pair(self.dns_name)
        return self._generate_pair_cryptography()

    def _generate_pair_cryptography(self) -> dict:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID

        now = self._now()

        # CA (certs.go:265-301)
        ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        ca_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, CA_NAME)]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=CA_VALIDITY_DAYS))
            .add_extension(
                x509.BasicConstraints(ca=True, path_length=None),
                critical=True,
            )
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True,
                    key_cert_sign=True,
                    crl_sign=True,
                    content_commitment=False,
                    key_encipherment=False,
                    data_encipherment=False,
                    key_agreement=False,
                    encipher_only=False,
                    decipher_only=False,
                ),
                critical=True,
            )
            .sign(ca_key, hashes.SHA256())
        )

        # server cert (certs.go:303-344)
        srv_key = rsa.generate_private_key(
            public_exponent=65537, key_size=2048
        )
        sans = [x509.DNSName(self.dns_name)]
        if self.dns_name != "localhost":
            sans.append(x509.DNSName("localhost"))
        sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
        srv_cert = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, self.dns_name)]
                )
            )
            .issuer_name(ca_name)
            .public_key(srv_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(
                now + datetime.timedelta(days=CERT_VALIDITY_DAYS)
            )
            .add_extension(
                x509.SubjectAlternativeName(sans), critical=False
            )
            .add_extension(
                x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH]
                ),
                critical=False,
            )
            .sign(ca_key, hashes.SHA256())
        )

        pem = serialization.Encoding.PEM
        return {
            "ca.crt": ca_cert.public_bytes(pem),
            # chain the CA so clients can verify with just tls.crt
            "tls.crt": srv_cert.public_bytes(pem)
            + ca_cert.public_bytes(pem),
            "tls.key": srv_key.private_bytes(
                pem,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        }

    def install_artifacts(self, artifacts: dict) -> None:
        """Write the ca.crt / tls.crt / tls.key triple via
        write-then-atomic-rename. With multiple replicas two concurrent
        `ensure()` callers are real, and a reader racing an in-place
        rewrite could load a tls.crt signed by a ca.crt it hasn't seen —
        a torn pair. os.rename within one directory is atomic, so every
        reader sees either the old artifact or the new one, never a
        partial write; the key lands BEFORE the certs so no reader can
        observe a cert whose key is still the old one's."""
        os.makedirs(self.cert_dir, exist_ok=True)
        for fname, path, mode in (
            ("tls.key", self.key_path, 0o600),
            ("ca.crt", self.ca_path, None),
            ("tls.crt", self.cert_path, None),
        ):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(artifacts[fname])
            if mode is not None:
                os.chmod(tmp, mode)
            os.rename(tmp, path)


# -- openssl-CLI fallback ----------------------------------------------------
# The jax_graft container ships no `cryptography` wheel; the TLS plane
# must not become import-poisoned there (and nothing may be pip
# installed), so the same CA/server-pair semantics are reproduced with
# the `openssl` binary. Same validity windows, same SANs, same chained
# tls.crt output.


def _openssl_expiry(data: bytes) -> Optional[datetime.datetime]:
    import subprocess

    try:
        out = subprocess.run(
            ["openssl", "x509", "-noout", "-enddate"],
            input=data, capture_output=True, timeout=30, check=True,
        ).stdout.decode()
    except Exception:
        return None
    # "notAfter=Jan  1 12:00:00 2036 GMT"
    _, _, stamp = out.strip().partition("=")
    stamp = stamp.replace(" GMT", "").strip()
    try:
        dt = datetime.datetime.strptime(stamp, "%b %d %H:%M:%S %Y")
    except ValueError:
        return None
    return dt.replace(tzinfo=datetime.timezone.utc)


def _openssl_generate_pair(dns_name: str) -> dict:
    import subprocess
    import tempfile

    def run(*args, **kw):
        subprocess.run(
            list(args), capture_output=True, timeout=120, check=True, **kw
        )

    with tempfile.TemporaryDirectory(prefix="gk-certgen-") as d:
        ca_key, ca_crt = f"{d}/ca.key", f"{d}/ca.crt"
        srv_key, srv_csr, srv_crt = (
            f"{d}/tls.key", f"{d}/srv.csr", f"{d}/srv.crt"
        )
        # an explicit -config (not -addext): the system openssl.cnf adds
        # its own default basicConstraints, and a certificate with the
        # extension twice fails chain verification
        ca_cnf = f"{d}/ca.cnf"
        with open(ca_cnf, "w") as f:
            f.write(
                "[req]\n"
                "distinguished_name=dn\n"
                "x509_extensions=v3_ca\n"
                "prompt=no\n"
                f"[dn]\nCN={CA_NAME}\n"
                "[v3_ca]\n"
                "basicConstraints=critical,CA:TRUE\n"
                "keyUsage=critical,digitalSignature,keyCertSign,cRLSign\n"
                "subjectKeyIdentifier=hash\n"
            )
        run(
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", ca_key, "-out", ca_crt,
            "-days", str(CA_VALIDITY_DAYS), "-config", ca_cnf,
        )
        run(
            "openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", srv_key, "-out", srv_csr,
            "-subj", f"/CN={dns_name}",
        )
        sans = [f"DNS:{dns_name}"]
        if dns_name != "localhost":
            sans.append("DNS:localhost")
        sans.append("IP:127.0.0.1")
        ext = f"{d}/ext.cnf"
        with open(ext, "w") as f:
            f.write(
                f"subjectAltName={','.join(sans)}\n"
                "extendedKeyUsage=serverAuth\n"
            )
        run(
            "openssl", "x509", "-req", "-in", srv_csr,
            "-CA", ca_crt, "-CAkey", ca_key, "-CAcreateserial",
            "-days", str(CERT_VALIDITY_DAYS), "-out", srv_crt,
            "-extfile", ext,
        )

        def read(p: str) -> bytes:
            with open(p, "rb") as f:
                return f.read()

        ca_pem = read(ca_crt)
        return {
            "ca.crt": ca_pem,
            # chain the CA so clients can verify with just tls.crt
            "tls.crt": read(srv_crt) + ca_pem,
            "tls.key": read(srv_key),
        }


VWH_GVK_ARGS = ("admissionregistration.k8s.io", "v1",
                "ValidatingWebhookConfiguration")


class CaBundleInjector:
    """Injects the rotator's CA bundle into a
    ValidatingWebhookConfiguration and re-injects on drift — the
    reference's injectCertToWebhook + ReconcileVWH self-healing loop
    (certs.go:183-263,468-515), driven through the EventSource seam so
    it works against the FakeCluster and the real apiserver alike."""

    def __init__(self, cluster, rotator: "CertRotator", vwh_name: str):
        from ..control.events import GVK

        self.cluster = cluster
        self.rotator = rotator
        self.vwh_name = vwh_name
        self.gvk = GVK(*VWH_GVK_ARGS)
        self.injections = 0
        self._unsubscribe = None

    def start(self) -> None:
        self.inject()
        self._unsubscribe = self.cluster.subscribe(self.gvk, self._on_event)
        # fleet rotators announce rotations (their own AND peers' picked
        # up from the shared Secret): re-inject immediately instead of
        # waiting for VWH drift to be noticed
        on_rotate = getattr(self.rotator, "on_rotate", None)
        if on_rotate is not None:
            on_rotate(lambda *_a, **_k: self.inject())

    def stop(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def _bundle_b64(self) -> str:
        import base64

        return base64.b64encode(self.rotator.ca_bundle()).decode()

    def _on_event(self, ev) -> None:
        meta = ev.obj.get("metadata") or {}
        if meta.get("name") != self.vwh_name or ev.type == "DELETED":
            return
        want = self._bundle_b64()
        hooks = ev.obj.get("webhooks") or []
        if any(
            (h.get("clientConfig") or {}).get("caBundle") != want
            for h in hooks
        ):
            self.inject()

    def inject(self) -> bool:
        obj = None
        getter = getattr(self.cluster, "get", None)
        if getter is not None:
            obj = getter(self.gvk, "", self.vwh_name)
        if obj is None:
            for cand in self.cluster.list(self.gvk):
                if (cand.get("metadata") or {}).get("name") == self.vwh_name:
                    obj = cand
                    break
        if obj is None:
            return False
        want = self._bundle_b64()
        changed = False
        hooks = obj.get("webhooks") or []
        for h in hooks:
            cc = h.setdefault("clientConfig", {})
            if cc.get("caBundle") != want:
                cc["caBundle"] = want
                changed = True
        if changed:
            self.cluster.apply(obj)
            self.injections += 1
        return changed
