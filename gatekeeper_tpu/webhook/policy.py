"""Validating admission handler.

Behavioral mirror of pkg/webhook/policy.go's validationHandler.Handle
(:141-221) and getDenyMessages (:224-282):

  * Gatekeeper's own service account is always allowed (:146-148);
  * DELETE reviews the existing object: oldObject replaces object, and a
    nil oldObject is a 500 (:150-165);
  * Gatekeeper's own CRs are dry-validated inline — ConstraintTemplates
    through CreateCRD, constraints through ValidateConstraint +
    enforcementAction validation (:167-178, :311-351) — user errors are
    422, internal errors 500;
  * namespaces excluded for the webhook process are allowed (:191-195);
  * the Namespace object is fetched and attached to the review
    (:354-369; here from a pluggable getter over the synced cache);
  * only `deny` results deny (403, messages joined with newlines);
    `dryrun` results are logged/counted only (:277-280).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..constraint.errors import ConstraintFrameworkError
from ..control import PROCESS_WEBHOOK, Excluder
from ..faults import AdmissionUnavailable

SERVICE_ACCOUNT_NAMESPACE = "gatekeeper-system"
SERVICE_ACCOUNT = (
    f"system:serviceaccount:{SERVICE_ACCOUNT_NAMESPACE}:gatekeeper-admin"
)


class TraceConfig:
    """Runtime per-request tracing rules from the Config CRD's
    spec.validation.traces (config_types.go:39-51), consulted per
    request by tracingLevel (policy.go:387-408): a request traces when
    BOTH its user and GVK match a rule; dump: "All" additionally dumps
    the whole engine state. Reconciled live by the config controller."""

    def __init__(self):
        self._lock = threading.Lock()
        self._traces: List[dict] = []

    def replace(self, traces: List[dict]) -> None:
        with self._lock:
            self._traces = [t for t in (traces or []) if isinstance(t, dict)]

    def level(self, request: Dict[str, Any]) -> tuple:
        """-> (trace_enabled, dump)."""
        user = (request.get("userInfo") or {}).get("username", "")
        kind = request.get("kind") or {}
        gvk = (
            kind.get("group", ""),
            kind.get("version", ""),
            kind.get("kind", ""),
        )
        enabled = dump = False
        with self._lock:
            for t in self._traces:
                if t.get("user") != user:
                    continue
                tk = t.get("kind") or {}
                if (
                    tk.get("group", ""),
                    tk.get("version", ""),
                    tk.get("kind", ""),
                ) == gvk:
                    enabled = True
                    if str(t.get("dump", "")).lower() == "all":
                        dump = True
        return enabled, dump


@dataclass
class AdmissionResponse:
    allowed: bool
    message: str = ""
    code: int = 200
    # RFC 6902 ops from the mutation plane; rendered as base64 JSON with
    # patchType: JSONPatch (the apiserver contract). None/[] = no patch.
    patch: Optional[List[Dict[str, Any]]] = None

    def to_dict(self, uid: Optional[str] = None) -> Dict[str, Any]:
        out: Dict[str, Any] = {"allowed": self.allowed}
        if uid is not None:
            out["uid"] = uid
        if self.message or self.code != 200:
            out["status"] = {
                "code": self.code,
                "message": self.message,
            }
        if self.patch:
            import base64
            import json as _json

            out["patchType"] = "JSONPatch"
            out["patch"] = base64.b64encode(
                _json.dumps(self.patch).encode()
            ).decode()
        return out


def unavailable_response(
    e: AdmissionUnavailable,
    fail_policy: str,
    metrics=None,
    log=None,
    span=None,
    plane: str = "validation",
) -> AdmissionResponse:
    """The bottom rung of the degradation ladder, shared by every
    admission plane (validation / mutation): the request was never
    evaluated (shed / expired / every rung down) — answer with the
    endpoint's fail-open/fail-closed envelope instead of a raw 500,
    explicitly and countably. Mirrors what the apiserver's
    failurePolicy would do on a webhook timeout, but within the
    caller's deadline."""
    if metrics is not None:
        metrics.record(
            "webhook_unavailable_responses_total", 1,
            plane=plane, policy=fail_policy, reason=e.reason,
        )
    if span is not None:
        span.set_attr(unavailable_reason=e.reason)
    if log is not None:
        log.error(
            "admission evaluation unavailable",
            process="admission",
            plane=plane,
            reason=e.reason,
            fail_policy=fail_policy,
            err=e,
        )
    if fail_policy == "closed":
        return AdmissionResponse(
            False,
            f"admission evaluation unavailable ({e.reason}): {e}",
            code=503,
        )
    return AdmissionResponse(
        True,
        f"admission evaluation unavailable ({e.reason}); "
        f"failing open: {e}",
    )


# the shed reasons recorded with verdict "shed" (vs "unavailable"):
# queue_full / predictive-miss / tenant-quota sheds and deadline expiry
# all mean "dropped by the admission plane", not "every rung down"
SHED_REASONS = ("queue_full", "deadline", "predicted_miss", "tenant_capped")


def note_unavailable_decision(
    decision: Dict[str, Any], e: AdmissionUnavailable
) -> None:
    """Stamp the typed not-evaluated outcome into a handler's decision
    dict (shared by the validation / mutation / agent planes): the
    verdict, the shed reason, and — for predictive sheds — the negative
    predicted slack and whether the tenant was over its fair share."""
    decision["verdict"] = (
        "shed" if e.reason in SHED_REASONS else "unavailable"
    )
    decision["reason"] = e.reason
    slack = getattr(e, "predicted_slack_ms", None)
    if slack is not None:
        decision["predicted_slack_ms"] = round(slack, 3)
    if getattr(e, "tenant_capped", False):
        decision["tenant_capped"] = True


class ValidationHandler:
    def __init__(
        self,
        client,
        target: str,
        excluder: Optional[Excluder] = None,
        namespace_getter: Optional[Callable[[str], Optional[dict]]] = None,
        log_denies: bool = False,
        metrics=None,
        trace_config: Optional[TraceConfig] = None,
        event_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        emit_admission_events: bool = False,
        trace_log: Optional[Callable[[str], None]] = None,
        logger=None,
        tracer=None,
        # what a request that could NOT be evaluated (shed under
        # overload, deadline expired, every evaluation rung down) gets:
        # "open" allows (the reference's failurePolicy: Ignore posture —
        # audit is the backstop), "closed" denies with a 503. Evaluation
        # ERRORS (a poisoned request) remain 500s regardless.
        fail_policy: str = "open",
        # obs.DecisionLog: every handled request leaves one "why"
        # record (verdict + violations + dispatch route/rows facts the
        # batcher stashed under the trace id), head+error-sampled and
        # rate-gated (docs/observability.md §Decision log)
        decision_log=None,
    ):
        from ..logs import null_logger

        if fail_policy not in ("open", "closed"):
            raise ValueError(
                f"fail_policy must be 'open' or 'closed', got {fail_policy!r}"
            )
        self.fail_policy = fail_policy
        self.decision_log = decision_log
        self.client = client
        from ..constraint.handler import handler_for

        # the target's handler owns review construction + exemption
        # hooks (docs/targets.md); resolved once — the registry is
        # fixed for the client's lifetime
        self.target_handler = handler_for(client, target)
        # optional obs.Tracer: every handled request becomes a trace
        # (span taxonomy in docs/observability.md); denial log records
        # carry the trace_id for correlation
        self.tracer = tracer
        self.target = target
        self.excluder = excluder
        self.namespace_getter = namespace_getter
        self.log_denies = log_denies
        self.log = logger if logger is not None else null_logger()
        self.metrics = metrics
        self.trace_config = trace_config
        # violation event emission (--emit-admission-events,
        # policy.go:253-273); the sink is the K8s Events stand-in
        self.event_sink = event_sink
        self.emit_admission_events = emit_admission_events
        self.trace_log = trace_log
        # bounded: soak replicas run with log_denies on for the
        # trace-id correlation contract, and a 100%-deny scenario must
        # churn this ring, not grow it for the process lifetime
        from collections import deque

        self.denied_log: Any = deque(maxlen=4096)
        self.traces: List[str] = []  # captured per-request traces

    # -- entry ---------------------------------------------------------------

    def handle(
        self, request: Dict[str, Any], trace_id: Optional[str] = None
    ) -> AdmissionResponse:
        import time as _time

        from ..obs import start_span

        t0 = _time.perf_counter()
        kind = request.get("kind") or {}
        with start_span(
            self.tracer,
            "handler",
            # an ingested W3C traceparent (or UID-derived id) becomes
            # THE trace id for this request's whole span tree — the
            # envelope, denial log, and /debug/traces all share it
            trace_id=trace_id,
            resource_kind=kind.get("kind", ""),
            resource_namespace=request.get("namespace", ""),
            resource_name=request.get("name", ""),
            operation=request.get("operation", ""),
            username=(request.get("userInfo") or {}).get("username", ""),
        ) as span:
            decision: Dict[str, Any] = {}
            resp = self._handle(request, span, decision)
            span.set_attr(
                admission_status=(
                    "allow" if resp.allowed
                    else ("error" if resp.code >= 500 else "deny")
                ),
                code=resp.code,
            )
        status = (
            "allow" if resp.allowed
            else ("error" if resp.code >= 500 else "deny")
        )
        duration_s = _time.perf_counter() - t0
        if self.metrics is not None:
            # the webhook stats reporter's surface (request_count +
            # request_duration_seconds tagged by admission_status,
            # pkg/webhook/stats_reporter.go:34-79); the sample carries
            # the request's trace id as an OpenMetrics exemplar so a
            # p99 bucket names a concrete trace to open
            self.metrics.record("request_count", 1, admission_status=status)
            self.metrics.observe(
                "request_duration_seconds",
                duration_s,
                exemplar=getattr(span, "trace_id", None),
                admission_status=status,
            )
        self._record_decision(
            request, resp, status, duration_s,
            getattr(span, "trace_id", None) or trace_id, decision,
        )
        return resp

    def _record_decision(
        self,
        request: Dict[str, Any],
        resp: "AdmissionResponse",
        status: str,
        duration_s: float,
        trace_id: Optional[str],
        decision: Dict[str, Any],
        plane: str = "validation",
    ) -> None:
        """One per-admission "why" record: verdict + violations +
        whatever dispatch facts the batch worker stashed under the
        trace id (route, partitions dispatched vs mask-skipped,
        rows_dispatched/rows_total, fetch/cache counts). A shed or
        unevaluable request records its typed reason as the verdict so
        overload is first-class in the decision stream."""
        if self.decision_log is None:
            return
        verdict = decision.pop("verdict", None) or status
        timeout = getattr(self, "request_timeout", None)
        slack_ms = (
            (timeout - duration_s) * 1e3 if timeout is not None else None
        )
        self.decision_log.record_decision(
            plane,
            verdict,
            code=resp.code,
            trace_id=trace_id,
            duration_ms=duration_s * 1e3,
            tenant={
                "namespace": request.get("namespace", ""),
                "username": (request.get("userInfo") or {}).get(
                    "username", ""
                ),
            },
            violations=decision.pop("violations", []),
            message=resp.message if not resp.allowed else "",
            deadline_slack_ms=slack_ms,
            operation=request.get("operation", ""),
            resource={
                "kind": (request.get("kind") or {}).get("kind", ""),
                "name": request.get("name", ""),
            },
            **decision,
        )

    def _handle(
        self, request: Dict[str, Any], span=None, decision=None
    ) -> AdmissionResponse:
        from ..obs import NOOP_SPAN

        if span is None:
            span = NOOP_SPAN
        if decision is None:
            decision = {}
        user = (request.get("userInfo") or {}).get("username", "")
        if user == SERVICE_ACCOUNT:
            decision["reason"] = "service_account"
            return AdmissionResponse(True, "Gatekeeper does not self-manage")

        request = dict(request)
        if request.get("operation") == "DELETE":
            if request.get("oldObject") is None:
                return AdmissionResponse(
                    False,
                    "For admission webhooks registered for DELETE operations, "
                    "please use Kubernetes v1.15.0+.",
                    code=500,
                )
            request["object"] = request.get("oldObject")

        user_err, err = self._validate_gatekeeper_resources(request)
        if err is not None:
            return AdmissionResponse(
                False, str(err), code=422 if user_err else 500
            )

        exempt_reason = self.target_handler.request_exempt(
            request, self.excluder, PROCESS_WEBHOOK
        )
        if exempt_reason is not None:
            decision["reason"] = "exempt"
            return AdmissionResponse(True, exempt_reason)

        trace_enabled = dump = False
        if self.trace_config is not None:
            trace_enabled, dump = self.trace_config.level(request)
        try:
            results = self._review(request, tracing=trace_enabled, span=span)
        except AdmissionUnavailable as e:
            # the typed not-evaluated verdicts (shed / deadline /
            # degraded / timeout) are first-class in the decision
            # stream — an overload story must be reconstructible from
            # the records alone
            note_unavailable_decision(decision, e)
            return self._unavailable_response(e, span)
        except Exception as e:
            return AdmissionResponse(False, str(e), code=500)
        if dump:
            self._emit_trace(self.client.dump())

        msgs = self._deny_messages(
            results, request, trace_id=span.trace_id, decision=decision
        )
        if msgs:
            return AdmissionResponse(False, "\n".join(msgs), code=403)
        return AdmissionResponse(True, "")

    def _unavailable_response(
        self, e: AdmissionUnavailable, span=None, plane: str = "validation"
    ) -> AdmissionResponse:
        return unavailable_response(
            e, fail_policy=self.fail_policy, metrics=self.metrics,
            log=self.log, span=span, plane=plane,
        )

    # -- pieces --------------------------------------------------------------

    def _emit_trace(self, text: str) -> None:
        self.traces.append(text)
        if self.trace_log is not None:
            self.trace_log(text)

    def _review(
        self, request: Dict[str, Any], tracing: bool = False, span=None
    ) -> List[Any]:
        from ..obs import start_span

        review = self._augment(request)
        with start_span(self.tracer, "dispatch", parent=span, route="serial"):
            responses = self.client.review(review, tracing=tracing)
        resp = responses.by_target.get(self.target)
        if tracing and resp is not None and resp.trace:
            self._emit_trace(resp.trace)
        return resp.results if resp is not None else []

    def _augment(self, request: Dict[str, Any]):
        return self.target_handler.augment_request(
            request, self.namespace_getter
        )

    def _deny_messages(
        self,
        results: List[Any],
        request: Dict[str, Any],
        trace_id: Optional[str] = None,
        decision: Optional[Dict[str, Any]] = None,
    ) -> List[str]:
        """getDenyMessages (:224-282): deny messages are
        '[denied by <constraint>] <msg>'; dryrun results are recorded
        but never deny. Every denial record carries the request's
        trace_id so /debug/traces explains the latency behind it, and
        the violated constraint set lands in the decision record."""
        log = (
            self.log.with_values(trace_id=trace_id)
            if trace_id is not None
            else self.log
        )
        msgs: List[str] = []
        violations: List[Dict[str, Any]] = []
        for r in results:
            cname = ((r.constraint or {}).get("metadata") or {}).get(
                "name", "?"
            )
            if r.enforcement_action in ("deny", "dryrun"):
                violations.append({
                    "constraint_kind": (r.constraint or {}).get("kind", ""),
                    "constraint_name": cname,
                    "action": r.enforcement_action,
                    "msg": (r.msg or "")[:256],
                })
            if (
                r.enforcement_action in ("deny", "dryrun")
                and self.log_denies
                # shed-burst containment: the decision log's shared
                # token bucket gates sibling denial-log appends too, so
                # a deny storm is bounded across BOTH obs sinks
                and (
                    self.decision_log is None
                    or self.decision_log.allow_denial_append()
                )
            ):
                # --log-denies (policy.go:240-252): one structured
                # record per violation with the reference's key set
                log.info(
                    "denied admission",
                    process="admission",
                    event_type="violation",
                    constraint_name=cname,
                    constraint_kind=(r.constraint or {}).get("kind", ""),
                    constraint_action=r.enforcement_action,
                    resource_kind=(request.get("kind") or {}).get(
                        "kind", ""
                    ),
                    resource_namespace=request.get("namespace", ""),
                    resource_name=request.get("name", ""),
                    request_username=(request.get("userInfo") or {}).get(
                        "username", ""
                    ),
                )
                self.denied_log.append(
                    {
                        "process": "admission",
                        "event_type": "violation",
                        "trace_id": trace_id,
                        "constraint_name": cname,
                        "constraint_action": r.enforcement_action,
                        "resource_namespace": request.get("namespace", ""),
                        "resource_name": request.get("name", ""),
                        "msg": r.msg,
                    }
                )
            if (
                r.enforcement_action in ("deny", "dryrun")
                and self.emit_admission_events
                and self.event_sink is not None
            ):
                dryrun = r.enforcement_action == "dryrun"
                self.event_sink(
                    {
                        "type": "Warning",
                        "reason": (
                            "DryrunViolation" if dryrun else "FailedAdmission"
                        ),
                        "process": "admission",
                        "event_type": "violation",
                        "constraint_name": cname,
                        "constraint_kind": (r.constraint or {}).get(
                            "kind", ""
                        ),
                        "constraint_action": r.enforcement_action,
                        "resource_kind": (request.get("kind") or {}).get(
                            "kind", ""
                        ),
                        "resource_namespace": request.get("namespace", ""),
                        "resource_name": request.get("name", ""),
                        "request_username": (
                            request.get("userInfo") or {}
                        ).get("username", ""),
                        "message": r.msg,
                    }
                )
            if r.enforcement_action == "deny":
                msgs.append(f"[denied by {cname}] {r.msg}")
        if decision is not None and violations:
            decision["violations"] = violations
        return msgs

    def _validate_gatekeeper_resources(self, request: Dict[str, Any]):
        """validateGatekeeperResources (:301-351): dry-validate GK's own
        CRs inline. Returns (user_error, error|None)."""
        kind = request.get("kind") or {}
        group = kind.get("group", "")
        obj = request.get("object")
        if group == "templates.gatekeeper.sh" and kind.get("kind") == (
            "ConstraintTemplate"
        ):
            try:
                self.client.create_crd(obj)
            except ConstraintFrameworkError as e:
                return True, e
            except Exception as e:
                return False, e
            return False, None
        if group == "constraints.gatekeeper.sh":
            try:
                self.client.validate_constraint(obj)
            except ConstraintFrameworkError as e:
                return True, e
            except Exception as e:
                return False, e
            action = ((obj or {}).get("spec") or {}).get("enforcementAction")
            if action is not None and action not in ("deny", "dryrun"):
                return False, ValueError(
                    f"Could not find the provided enforcementAction value "
                    f"within the supported list: {action!r}"
                )
            return False, None
        return False, None
