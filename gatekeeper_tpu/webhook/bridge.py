"""Admission serving bridge, Python/JAX back half.

Pairs with native/bridge_frontend.cpp (SURVEY §2.4 row 3 / §7 step 5):
the C++ frontend terminates the admission HTTP traffic on native
threads and streams each AdmissionReview body over a Unix socket as
length-prefixed frames; this server parses them, routes through the
SAME micro-batching ValidationHandler the in-process webhook uses (so
concurrent requests coalesce into fused device dispatches), and replies
with the complete AdmissionReview response JSON. A frontend that gets
no reply within its --deadline-ms fails open (the reference's
failurePolicy: Ignore posture; audit is the backstop).

`build_frontend()` compiles the C++ half on demand with the same
lazy-build discipline as the native flattener (source ships, binaries
don't).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import threading
from typing import Optional

from ..faults import fire
from ..logs import null_logger


def build_frontend(force: bool = False) -> Optional[str]:
    """Compile bridge_frontend.cpp -> cached binary; None if no
    toolchain."""
    src = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native",
        "bridge_frontend.cpp",
    )
    out_dir = os.environ.get(
        "GATEKEEPER_TPU_NATIVE_DIR",
        os.path.expanduser("~/.cache/gatekeeper_tpu/native"),
    )
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, "bridge_frontend")
    if (
        not force
        and os.path.exists(out)
        and os.path.getmtime(out) >= os.path.getmtime(src)
    ):
        return out
    try:
        subprocess.run(
            [
                "g++", "-O2", "-std=c++17", "-pthread",
                "-o", out + ".tmp", src,
            ],
            check=True,
            capture_output=True,
        )
        os.replace(out + ".tmp", out)
        return out
    except (OSError, subprocess.CalledProcessError):
        return None


class BatchBridgeServer:
    """Unix-socket frame server feeding the micro-batching handler.

    Frames carry "<http path>\\n<body>": /v1/admit routes to `handler`,
    /v1/admitlabel to `label_handler` (the ns-label webhook, mirroring
    webhook/server.py's in-process routing)."""

    def __init__(self, handler, socket_path: str, label_handler=None,
                 logger=None):
        self.handler = handler  # ValidationHandler-compatible .handle()
        self.label_handler = label_handler
        self.socket_path = socket_path
        self.log = logger if logger is not None else null_logger()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.requests_served = 0

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(1024)
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _recv_full(self, conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            header = self._recv_full(conn, 4)
            if header is None:
                return
            (length,) = struct.unpack("!I", header)
            if length > 64 << 20:
                return
            body = self._recv_full(conn, length)
            if body is None:
                return
            out = self._process(body)
            try:
                conn.sendall(struct.pack("!I", len(out)) + out)
            except OSError:
                pass

    def _process(self, frame: bytes) -> bytes:
        try:
            # named fault point (docs/robustness.md): "error" simulates
            # a backend processing crash (the frame gets the 500 doc and
            # the frontend's --deadline-ms fail-open is the backstop);
            # "hang" a stalled backend worker
            fire("bridge.process")
            path, _, body = frame.partition(b"\n")
            handler = self.handler
            if path == b"/v1/admitlabel" and self.label_handler is not None:
                handler = self.label_handler
            review = json.loads(body)
            request = review.get("request") or {}
            resp = handler.handle(request)
            doc = {
                "apiVersion": review.get(
                    "apiVersion", "admission.k8s.io/v1"
                ),
                "kind": "AdmissionReview",
                "response": resp.to_dict(uid=request.get("uid")),
            }
        except Exception as e:
            self.log.error("bridge request failed", err=e)
            doc = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "response": {
                    "uid": "",
                    "allowed": False,
                    "status": {"code": 500, "message": str(e)},
                },
            }
        self.requests_served += 1
        return json.dumps(doc).encode()


class BridgeStack:
    """Backend + compiled frontend as one unit (tests/bench/demo)."""

    def __init__(
        self,
        client,
        target: str,
        socket_path: str,
        port: int = 0,
        deadline_ms: int = 2000,
        window_ms: float = 2.0,
        exempt_namespaces=(),
        metrics=None,
        tracer=None,
        max_queue=None,
        # admission scheduling (docs/operations.md §Admission
        # scheduling): the bridge rides the same MicroBatcher seam,
        # so the deadline policy + fair-share quotas apply here too
        sched_policy: str = "fifo",
        slo=None,
        attributor=None,
        **handler_kwargs,
    ):
        from .namespacelabel import NamespaceLabelHandler
        from .server import (
            DEFAULT_MAX_QUEUE,
            BatchedValidationHandler,
            MicroBatcher,
        )

        self.batcher = MicroBatcher(
            client, target, window_ms=window_ms,
            metrics=metrics, tracer=tracer,
            max_queue=max_queue if max_queue is not None
            else DEFAULT_MAX_QUEUE,
            sched_policy=sched_policy, slo=slo, attributor=attributor,
        )
        handler_kwargs.setdefault("metrics", metrics)
        handler_kwargs.setdefault("tracer", tracer)
        self.handler = BatchedValidationHandler(
            self.batcher, **handler_kwargs
        )
        self.backend = BatchBridgeServer(
            self.handler,
            socket_path,
            label_handler=NamespaceLabelHandler(exempt_namespaces),
        )
        self.socket_path = socket_path
        self.deadline_ms = deadline_ms
        self.requested_port = port
        self.port: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        binary = build_frontend()
        if binary is None:
            raise RuntimeError("no C++ toolchain for the bridge frontend")
        self.batcher.start()
        self.backend.start()
        try:
            self._proc = subprocess.Popen(
                [
                    binary,
                    "--port", str(self.requested_port),
                    "--backend", self.socket_path,
                    "--deadline-ms", str(self.deadline_ms),
                ],
                stdout=subprocess.PIPE,
                text=True,
            )
            line = self._proc.stdout.readline().strip()
            if not line.startswith("LISTENING "):
                raise RuntimeError(f"frontend failed to start: {line!r}")
            self.port = int(line.split()[1])
        except Exception:
            # never leak the running batcher/backend (callers invoke
            # start() before entering their try/finally)
            self.stop()
            raise

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
            self._proc = None
        self.backend.stop()
        self.batcher.stop()
