"""The verdict-integrity plane (docs/robustness.md §Verdict integrity).

Three detection tiers feed the existing quarantine machinery:

  1. **Canary rows** — the driver packs K synthetic reviews with
     interpreter-pinned golden digests into the padding slots every
     fused dispatch already wastes, and reports the device's canary
     verdicts here (`check_canaries`). A digest mismatch is silent-
     data-corruption evidence against that device, never a policy
     outcome — canary results are stripped before any merge.
  2. **Sampled shadow oracle** — a deterministic CRC(trace_id)
     fraction of live admissions (`note_live`) re-evaluates
     asynchronously post-response on the host interpreter; a
     fused-vs-oracle divergence emits a typed `verdict_divergence`
     decision record plus ONE FlightRecorder capture per burst
     (the recorder's debounce coalesces).
  3. **SDC quarantine + golden self-test** — a per-device mismatch
     ledger (distinct from breaker failure counts) trips
     `PartitionDispatcher.quarantine(device, reason="corruption")`
     at `quarantine_threshold` consecutive mismatching batches; the
     plan rebuild re-homes the device's partitions exactly as a
     breaker trip would. The device heals ONLY after `selftest`
     replays the golden batch clean — corruption quarantine never
     self-heals on a timer the way breaker HALF_OPEN does, because a
     corrupting device that "recovers" silently is the failure mode
     this plane exists to catch.

Fault points `integrity.canary` / `integrity.shadow` /
`integrity.selftest` (plus their device-labeled forms) let the chaos
suite force a bit-flip at each tier without real broken hardware.

Thread-safety: the plane's lock is a leaf — it never calls back into
the driver or dispatcher while held. Quarantine/heal calls happen off
the plane lock, and the driver reports canary results only AFTER
releasing its serving mutex.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from ..faults.injection import FaultError, device_point, fire
from .canary import DEFAULT_K, result_digest, synth_reviews

__all__ = ["IntegrityPlane", "shadow_sampled"]


def shadow_sampled(trace_id: Optional[str], sample_n: int) -> bool:
    """Deterministic shadow-oracle sampling decision: CRC32 of the
    trace id, 1-in-`sample_n`. The same hash family the decision log
    uses for keep sampling — every replica makes the SAME decision for
    the same trace, so a fleet's shadow coverage is disjoint-free and
    a divergence report is reproducible by replaying the trace id."""
    if not trace_id or sample_n <= 0:
        return False
    return zlib.crc32(str(trace_id).encode()) % sample_n == 0


class IntegrityPlane:
    """Process-wide verdict-integrity state: golden canary sets, the
    per-device mismatch ledger, the shadow-oracle queue/worker, and
    self-test healing. One instance per Runner, wired into the driver
    (`set_integrity`), the micro-batchers, and the PartitionDispatcher.
    """

    def __init__(
        self,
        metrics: Optional[Any] = None,
        decisions: Optional[Any] = None,
        recorder: Optional[Any] = None,
        store: Optional[Any] = None,
        canaries_per_dispatch: int = DEFAULT_K,
        shadow_sample_n: int = 8,
        quarantine_threshold: int = 2,
        selftest_interval_s: float = 30.0,
        shadow_queue_max: int = 256,
    ):
        self.metrics = metrics
        self.decisions = decisions
        self.recorder = recorder
        self.store = store  # compile.ProgramStore (golden sidecars) or None
        self.k = max(1, int(canaries_per_dispatch))
        self.shadow_sample_n = max(0, int(shadow_sample_n))
        self.quarantine_threshold = max(1, int(quarantine_threshold))
        self.selftest_interval_s = float(selftest_interval_s)
        self._lock = threading.RLock()
        self._tl = threading.local()  # .suppress — no canaries in selftest
        # (target, sigkey) -> {"reviews": [...], "digests": [...]}
        self._golden: Dict[Any, Dict[str, Any]] = {}
        # per-device ledger: consecutive mismatching batches + totals
        self._consecutive: Dict[int, int] = {}
        self._mismatches: Dict[int, int] = {}
        self._quarantined: Dict[int, Dict[str, Any]] = {}
        self.canary_rows = 0
        self.canary_batches = 0
        self.canary_mismatch_batches = 0
        self.shadow_sampled_n = 0
        self.shadow_divergences = 0
        self.shadow_skipped_stale = 0
        self.shadow_dropped = 0
        self.selftest_pass = 0
        self.selftest_fail = 0
        self._selftest_last: Dict[int, float] = {}
        self._shadow_q: deque = deque(maxlen=max(1, int(shadow_queue_max)))
        self._shadow_event = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self._driver = None
        self._client = None
        self._dispatcher = None

    # -- wiring --------------------------------------------------------------

    def bind_driver(self, driver) -> None:
        """Called by TpuDriver.set_integrity: the driver reference
        serves constraint-generation staleness checks and self-test
        dispatch routing."""
        self._driver = driver

    def attach_client(self, client) -> None:
        """The shadow oracle re-evaluates through Client.review_host
        (the same host rung the breaker degrades to)."""
        self._client = client

    def attach_dispatcher(self, dispatcher) -> None:
        """The PartitionDispatcher whose quarantine the mismatch
        ledger trips (None = monolithic deployment: detection still
        runs, quarantine state is plane-local only)."""
        self._dispatcher = dispatcher

    def close(self) -> None:
        self._closed = True
        self._shadow_event.set()

    # -- tier 1: canary rows -------------------------------------------------

    @property
    def _suppressed(self) -> bool:
        return bool(getattr(self._tl, "suppress", False))

    def canaries_for(
        self,
        target: str,
        sigkey: str,
        constraints: Sequence[Dict[str, Any]],
        interp,
        slots: int,
    ) -> List[Dict[str, Any]]:
        """The canary reviews the driver should pack into this
        dispatch's padding slots (at most min(k, slots); empty during
        a self-test replay so the golden batch itself is never
        re-canaried). First call per (target, signature) derives the
        golden set: synthesized reviews evaluated through `interp` —
        the driver's host-interpreter closure over the SAME constraint
        set the fused dispatch serves — and pinned as per-review
        digests (persisted as a ProgramStore sidecar when a store is
        wired)."""
        if self._suppressed or slots <= 0 or not constraints:
            return []
        entry = self._golden_entry(target, sigkey, constraints, interp)
        if entry is None:
            return []
        return entry["reviews"][: min(self.k, int(slots))]

    def _golden_entry(
        self, target, sigkey, constraints, interp
    ) -> Optional[Dict[str, Any]]:
        key = (target, sigkey)
        with self._lock:
            entry = self._golden.get(key)
        if entry is not None:
            return entry
        entry = self._sidecar_load(target, sigkey)
        if entry is None:
            try:
                reviews = synth_reviews(constraints, self.k)
                digests = [result_digest(interp(r)) for r in reviews]
            except Exception:
                return None  # derivation must never fail a dispatch
            entry = {"reviews": reviews, "digests": digests}
            self._sidecar_save(target, sigkey, entry)
        with self._lock:
            self._golden.setdefault(key, entry)
            return self._golden[key]

    def golden_for(
        self, target: str, sigkey: str, constraints, interp
    ) -> Optional[Dict[str, Any]]:
        """Public golden-set accessor (the warm-swap gate and the
        analysis canary gate use it): {"reviews", "digests"}."""
        return self._golden_entry(target, sigkey, constraints, interp)

    def check_canaries(
        self,
        target: str,
        sigkey: str,
        device: int,
        canary_results: Sequence[Sequence[Any]],
        subset=None,
        plane: str = "validate",
    ) -> bool:
        """Compare one dispatch's canary verdicts against the golden
        digests. Returns True when clean. A mismatch (or an armed
        `integrity.canary` fault — the injectable bit-flip) increments
        the device's ledger; `quarantine_threshold` CONSECUTIVE
        mismatching batches trip corruption quarantine. Called by the
        driver AFTER its serving mutex is released."""
        if not canary_results:
            return True
        with self._lock:
            entry = self._golden.get((target, sigkey))
        if entry is None:
            return True
        device = int(device)
        corrupted = False
        try:
            fire("integrity.canary")
            fire(device_point("integrity.canary", device))
        except FaultError:
            corrupted = True
        got = [result_digest(rs) for rs in canary_results]
        expect = entry["digests"][: len(got)]
        mismatch = corrupted or got != expect
        if self.metrics is not None:
            self.metrics.record(
                "integrity_canary_rows_total", len(got), device=device
            )
        trip = False
        with self._lock:
            self.canary_rows += len(got)
            self.canary_batches += 1
            if mismatch:
                self.canary_mismatch_batches += 1
                self._mismatches[device] = (
                    self._mismatches.get(device, 0) + 1
                )
                n = self._consecutive.get(device, 0) + 1
                self._consecutive[device] = n
                if (
                    n >= self.quarantine_threshold
                    and device not in self._quarantined
                ):
                    self._quarantined[device] = {
                        "reason": "corruption",
                        "target": target,
                        "signature": sigkey,
                        "subset": (
                            sorted(subset) if subset is not None else None
                        ),
                        "plane": plane,
                        "since": time.monotonic(),
                    }
                    trip = True
            else:
                self._consecutive[device] = 0
        if mismatch and self.metrics is not None:
            self.metrics.record(
                "integrity_canary_mismatch_total", 1, device=device
            )
        if trip:
            disp = self._dispatcher
            if disp is not None:
                try:
                    disp.quarantine(device, reason="corruption")
                except TypeError:
                    disp.quarantine(device)
        return not mismatch

    # -- tier 2: sampled shadow oracle ---------------------------------------

    def note_live(
        self,
        trace_id: Optional[str],
        obj: Any,
        results: Sequence[Any],
        plane: str = "validate",
        **facts,
    ) -> bool:
        """Post-response hook from the micro-batchers: maybe enqueue
        this admission for asynchronous host-oracle re-evaluation.
        Returns True when sampled. Only the live verdict DIGEST is
        retained up front — the repro bundle (full review) rides along
        for the flight record, never re-serialized on the hot path."""
        if self._closed or self._client is None:
            return False
        if not shadow_sampled(trace_id, self.shadow_sample_n):
            return False
        if self.metrics is not None:
            self.metrics.record(
                "integrity_shadow_sampled_total", 1, plane=plane
            )
        gen = getattr(self._driver, "_constraint_gen", None)
        with self._lock:
            self.shadow_sampled_n += 1
            if len(self._shadow_q) == self._shadow_q.maxlen:
                self.shadow_dropped += 1
        self._shadow_q.append(
            (trace_id, obj, result_digest(results), plane, gen, facts)
        )
        self._ensure_worker()
        self._shadow_event.set()
        return True

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._worker_loop,
                name="integrity-shadow",
                daemon=True,
            )
            self._worker.start()

    def _worker_loop(self) -> None:
        while not self._closed:
            self._shadow_event.wait(timeout=1.0)
            self._shadow_event.clear()
            while True:
                try:
                    item = self._shadow_q.popleft()
                except IndexError:
                    break
                try:
                    self._shadow_eval(*item)
                except Exception:
                    pass  # the oracle must never take the plane down
            self._maybe_selftests()

    def drain_shadow(self, timeout_s: float = 5.0) -> None:
        """Synchronously work the shadow queue dry (tests/bench): runs
        evaluations inline on the caller's thread so assertions don't
        race the worker."""
        deadline = time.monotonic() + timeout_s
        while self._shadow_q and time.monotonic() < deadline:
            try:
                item = self._shadow_q.popleft()
            except IndexError:
                break
            try:
                self._shadow_eval(*item)
            except Exception:
                pass

    def _shadow_eval(
        self, trace_id, obj, live_digest, plane, gen, facts
    ) -> None:
        client = self._client
        if client is None:
            return
        now_gen = getattr(self._driver, "_constraint_gen", None)
        if gen is not None and now_gen != gen:
            # the corpus churned since the live verdict: the oracle
            # would evaluate a DIFFERENT policy — a mismatch here is
            # churn, not corruption
            with self._lock:
                self.shadow_skipped_stale += 1
            return
        corrupted = False
        try:
            fire("integrity.shadow")
        except FaultError:
            corrupted = True
        resps = client.review_host(obj)
        oracle_results: List[Any] = []
        for resp in getattr(resps, "by_target", {}).values():
            oracle_results.extend(resp.results)
        oracle_digest = result_digest(oracle_results)
        if not corrupted and oracle_digest == live_digest:
            return
        with self._lock:
            self.shadow_divergences += 1
        if self.metrics is not None:
            self.metrics.record(
                "integrity_shadow_divergence_total", 1, plane=plane
            )
        if self.decisions is not None:
            try:
                self.decisions.record_decision(
                    plane,
                    "verdict_divergence",
                    code=500,
                    trace_id=trace_id,
                    message="fused verdict diverged from host oracle",
                    live_digest=live_digest,
                    oracle_digest=oracle_digest,
                    **facts,
                )
            except Exception:
                pass
        if self.recorder is not None:
            try:
                # debounce in the recorder coalesces a burst of
                # divergences into ONE record carrying the repro bundle
                self.recorder.trigger(
                    "verdict_divergence",
                    trace_id=trace_id,
                    plane=plane,
                    review=obj,
                    live_digest=live_digest,
                    oracle_digest=oracle_digest,
                    **facts,
                )
            except Exception:
                pass

    # -- tier 3: golden self-test + heal -------------------------------------

    def _maybe_selftests(self) -> None:
        if self.selftest_interval_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            due = [
                d
                for d in self._quarantined
                if now - self._selftest_last.get(d, 0.0)
                >= self.selftest_interval_s
            ]
        for device in due:
            self._selftest_last[device] = now
            try:
                self.selftest(device)
            except Exception:
                pass

    def selftest(self, device: int) -> bool:
        """Replay the golden batch against the suspect device and heal
        on a clean run. The `integrity.selftest` fault point (plain and
        device-labeled) injects a still-corrupting device; a corruption
        quarantine can ONLY clear through this path — there is no
        timer-driven half-open for SDC."""
        device = int(device)
        with self._lock:
            info = self._quarantined.get(device)
        ok = True
        try:
            fire("integrity.selftest")
            fire(device_point("integrity.selftest", device))
        except FaultError:
            ok = False
        if ok and info is not None:
            ok = self._replay_golden(device, info)
        if self.metrics is not None:
            self.metrics.record(
                "integrity_selftest_total",
                1,
                result="pass" if ok else "fail",
            )
        with self._lock:
            if ok:
                self.selftest_pass += 1
                self._quarantined.pop(device, None)
                self._consecutive[device] = 0
            else:
                self.selftest_fail += 1
        if ok:
            disp = self._dispatcher
            if disp is not None:
                try:
                    disp.heal(device)
                except Exception:
                    pass
        return ok

    def _replay_golden(self, device: int, info: Dict[str, Any]) -> bool:
        drv = self._driver
        target = info.get("target")
        with self._lock:
            entry = self._golden.get((target, info.get("signature")))
        if drv is None or entry is None:
            return True  # nothing to replay against — the fault point
            # above remains the injectable corruption signal
        path = f'hooks["{target}"].violation'
        inputs = [{"review": r} for r in entry["reviews"]]
        self._tl.suppress = True  # golden batch must not re-canary
        try:
            subset = info.get("subset")
            if subset:
                resps = drv.query_many_subset(
                    path, inputs, subset, device=device
                )
            else:
                resps = drv.query_many(path, inputs)
            got = [result_digest(r.results) for r in resps]
            return got == entry["digests"][: len(got)]
        except Exception:
            return False
        finally:
            self._tl.suppress = False

    # -- golden sidecars (compile.ProgramStore) ------------------------------

    def _sidecar_path(self, target: str, sigkey: str) -> Optional[str]:
        store = self.store
        root = getattr(store, "artifacts_dir", None)
        if not root:
            return None
        h = zlib.crc32(f"{target}|{sigkey}".encode())
        return os.path.join(root, f"canary-{h:08x}.json")

    def _sidecar_load(self, target, sigkey) -> Optional[Dict[str, Any]]:
        path = self._sidecar_path(target, sigkey)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
            reviews = doc.get("reviews")
            digests = doc.get("digests")
            if (
                isinstance(reviews, list)
                and isinstance(digests, list)
                and len(reviews) == len(digests)
            ):
                return {"reviews": reviews, "digests": digests}
        except Exception:
            pass
        return None

    def _sidecar_save(self, target, sigkey, entry) -> None:
        path = self._sidecar_path(target, sigkey)
        if path is None:
            return
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "target": target,
                        "signature": sigkey,
                        "reviews": entry["reviews"],
                        "digests": entry["digests"],
                    },
                    f,
                )
            os.replace(tmp, path)
        except Exception:
            pass

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """/debug/integrity + /readyz stats.integrity payload."""
        now = time.monotonic()
        with self._lock:
            per_device = {
                str(d): {
                    "mismatches": self._mismatches.get(d, 0),
                    "consecutive": self._consecutive.get(d, 0),
                }
                for d in set(self._mismatches) | set(self._consecutive)
                if self._mismatches.get(d, 0)
                or self._consecutive.get(d, 0)
            }
            quarantined = {
                str(d): {
                    "reason": info.get("reason"),
                    "target": info.get("target"),
                    "signature": info.get("signature"),
                    "plane": info.get("plane"),
                    "for_s": round(now - info.get("since", now), 3),
                }
                for d, info in self._quarantined.items()
            }
            return {
                "canary": {
                    "golden_sets": len(self._golden),
                    "per_dispatch": self.k,
                    "rows": self.canary_rows,
                    "batches": self.canary_batches,
                    "mismatch_batches": self.canary_mismatch_batches,
                    "per_device": per_device,
                },
                "shadow": {
                    "sample_n": self.shadow_sample_n,
                    "sampled": self.shadow_sampled_n,
                    "divergences": self.shadow_divergences,
                    "skipped_stale": self.shadow_skipped_stale,
                    "dropped": self.shadow_dropped,
                    "queue": len(self._shadow_q),
                },
                "selftest": {
                    "pass": self.selftest_pass,
                    "fail": self.selftest_fail,
                    "interval_s": self.selftest_interval_s,
                },
                "quarantined": quarantined,
                "quarantine_threshold": self.quarantine_threshold,
            }
