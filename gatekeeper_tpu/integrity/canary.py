"""Canary synthesis + verdict digesting (docs/robustness.md §Verdict
integrity).

A canary is a synthetic review whose ground-truth verdict set is
computed ONCE on the host interpreter and pinned as a digest; the
driver then rides K canaries in the padding slots every fused dispatch
already wastes (`padding_waste_rows_total`) and compares the device's
answer against the pinned digest. By the driver-parity contract the
fused path must reproduce the interpreter verdicts byte-for-byte, so
ANY digest mismatch is a corruption signal — never a policy outcome.

Synthesis is deterministic: the same constraint set always derives the
same canary reviews (and therefore the same golden digests) on every
replica, so golden sidecars are portable and a fleet's canary verdicts
are comparable. Reviews are mined from the constraints themselves —
parameter strings (denied registries, required annotation keys, memory
ceilings) are folded into pod shapes engineered to VIOLATE typical
templates, because a canary whose verdict set is empty cannot catch a
device that silently suppresses violations.
"""

from __future__ import annotations

import hashlib
import json
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "result_digest",
    "split_digests",
    "synth_agent_reviews",
    "synth_reviews",
]

# how many distinct canary shapes synth_reviews derives by default —
# small on purpose: canaries ride free in padding slots, but golden
# derivation pays one interpreter evaluation per canary per signature
DEFAULT_K = 4


def _stable(s: str) -> int:
    """Deterministic small hash (NOT Python's salted hash())."""
    return zlib.crc32(s.encode("utf-8", "replace"))


def _mine_params(constraints: Sequence[Dict[str, Any]]) -> Dict[str, list]:
    """Pull the parameter atoms canary pods should embed: annotation /
    label keys a template may require, registry prefixes it may deny.
    Best-effort — an unrecognised parameter shape just mines nothing."""
    ann_keys: List[str] = []
    label_keys: List[str] = []
    registries: List[str] = []
    for c in constraints:
        spec = c.get("spec") or {}
        params = spec.get("parameters") or {}
        if not isinstance(params, dict):
            continue
        for key, into in (
            ("annotations", ann_keys),
            ("labels", label_keys),
            ("registries", registries),
            ("repos", registries),
        ):
            v = params.get(key)
            if isinstance(v, list):
                for item in v:
                    if isinstance(item, str):
                        into.append(item)
                    elif isinstance(item, dict):
                        k = item.get("key") or item.get("name")
                        if isinstance(k, str):
                            into.append(k)
    return {
        "annotations": ann_keys,
        "labels": label_keys,
        "registries": registries,
    }


def _canary_metadata(i: int, mined: Dict[str, list]) -> Dict[str, Any]:
    metadata: Dict[str, Any] = {"name": f"integrity-canary-{i}"}
    if i % 3 == 1:
        # compliant-ish variant: carries every mined annotation/label
        # key so "required X" templates see this one pass
        metadata["annotations"] = {
            k: "integrity-canary" for k in mined["annotations"]
        } or {"integrity.gatekeeper/canary": "true"}
        metadata["labels"] = {
            k: "canary" for k in mined["labels"]
        } or {"app": "integrity-canary"}
    return metadata


def _canary_pod(i: int, mined: Dict[str, list]) -> Dict[str, Any]:
    """One deterministic pod spec engineered to trip common template
    families: index 0 is maximally-violating (no labels/annotations,
    denied-registry `:latest` image, absurd memory, privileged), later
    indices flip one dimension each so single-constraint corruption
    still has a verdict delta to corrupt."""
    registries = mined["registries"] or ["docker.io/"]
    reg = registries[i % len(registries)]
    image = (
        f"{reg}library/canary:latest"
        if i % 2 == 0
        else "pinned.example.com/canary:v1.2.3"
    )
    metadata = _canary_metadata(i, mined)
    memory = "64Gi" if i % 2 == 0 else "64Mi"
    container: Dict[str, Any] = {
        "name": "c0",
        "image": image,
        "resources": {"limits": {"memory": memory}},
    }
    if i % 3 != 1:
        # violating variants also run privileged, so pod-security
        # templates (privileged-container family) have a verdict to
        # corrupt; the compliant variant stays unprivileged
        container["securityContext"] = {"privileged": True}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": metadata,
        "spec": {"containers": [container]},
    }


def _canary_service(i: int, mined: Dict[str, list]) -> Dict[str, Any]:
    """A Service-shaped canary for constraints whose match kinds never
    see a Pod (the block-nodeport family): violating variants ask for
    NodePort, the compliant one stays ClusterIP."""
    metadata = _canary_metadata(i, mined)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": metadata,
        "spec": {
            "type": "ClusterIP" if i % 3 == 1 else "NodePort",
            "ports": [{"port": 80, "targetPort": 8080}],
        },
    }


def _mine_kinds(constraints: Sequence[Dict[str, Any]]) -> List[str]:
    """Distinct object kinds the constraint set's match blocks name
    (order-stable). Empty / wildcard match blocks contribute nothing —
    the caller falls back to Pod."""
    seen: List[str] = []
    for c in constraints:
        match = (c.get("spec") or {}).get("match") or {}
        for sel in match.get("kinds") or []:
            if not isinstance(sel, dict):
                continue
            for k in sel.get("kinds") or []:
                if isinstance(k, str) and k != "*" and k not in seen:
                    seen.append(k)
    return seen


def synth_reviews(
    constraints: Sequence[Dict[str, Any]],
    k: int = DEFAULT_K,
    group_kind: Tuple[str, str, str] = ("", "v1", "Pod"),
) -> List[Dict[str, Any]]:
    """Derive `k` deterministic gkReview dicts (the post-handle_review
    shape the driver evaluates) from a constraint set. Alternates
    cluster-scoped reviews (which can never autoreject — match.py's
    review_autorejects) with namespaced reviews carrying an `_unstable`
    namespace object, so namespaceSelector templates get coverage
    without tripping the not-synced-namespace autoreject."""
    mined = _mine_params(constraints)
    seed = _stable(
        "|".join(
            sorted(
                f'{c.get("kind", "")}/'
                f'{(c.get("metadata") or {}).get("name", "")}'
                for c in constraints
            )
        )
    )
    group, version, kind = group_kind
    # spread the kinds the match blocks actually name across the set
    # in contiguous blocks (a Service-only constraint set would
    # otherwise never see a canary it can match; blocks, not
    # round-robin, so each kind still gets both the violating and the
    # compliant index parities); unrecognised kinds fall back to pods.
    # A set whose match blocks name nothing concrete (wildcard / no
    # match) gets BOTH built-in shapes, so kind-specific templates
    # reached via a wildcard match still see a shape they can convict
    kinds = _mine_kinds(constraints) or [kind, "Service"]
    n = max(1, int(k))
    reviews: List[Dict[str, Any]] = []
    for i in range(n):
        obj_kind = kinds[(i * len(kinds)) // n]
        if obj_kind == "Service":
            obj = _canary_service(i, mined)
        else:
            obj_kind = kind
            obj = _canary_pod(i, mined)
        review: Dict[str, Any] = {
            "uid": f"integrity-canary-{seed:08x}-{i}",
            "kind": {"group": group, "version": version,
                     "kind": obj_kind},
            "operation": "CREATE",
            "name": obj["metadata"]["name"],
            "userInfo": {"username": "system:integrity-canary"},
            "object": obj,
            "_unstable": {},
        }
        if i % 2 == 1:
            ns = f"canary-ns-{i}"
            review["namespace"] = ns
            obj["metadata"]["namespace"] = ns
            # the attached namespace object suppresses autoreject and
            # feeds namespaceSelector matching, mirroring what
            # augment_request does for a synced namespace
            review["_unstable"] = {
                "namespace": {
                    "apiVersion": "v1",
                    "kind": "Namespace",
                    "metadata": {
                        "name": ns,
                        "labels": {"integrity-canary": "true"},
                    },
                }
            }
        reviews.append(review)
    return reviews


def _mine_agent(constraints: Sequence[Dict[str, Any]]) -> Dict[str, list]:
    """Parameter/match atoms for agent-action canaries: concrete tool
    names satisfying the constraints' tool globs, capability label
    keys their selectors require, and the allow-list values (commands,
    domains, required argument names) the parameters pin."""
    tools: List[str] = []
    caps: List[str] = []
    allowed: List[str] = []
    domains: List[str] = []
    required: List[str] = []
    for c in constraints:
        spec = c.get("spec") or {}
        match = spec.get("match") or {}
        for t in match.get("tools") or []:
            if not isinstance(t, str):
                continue
            if t == "*":
                tool = "canary.invoke"
            elif t.endswith(".*"):
                tool = f"{t[:-2]}.canary"
            else:
                tool = t
            if tool not in tools:
                tools.append(tool)
        sel = match.get("capabilities")
        if isinstance(sel, dict):
            for k in (sel.get("matchLabels") or {}):
                if isinstance(k, str) and k not in caps:
                    caps.append(k)
            for expr in sel.get("matchExpressions") or []:
                k = (expr or {}).get("key")
                if isinstance(k, str) and (expr or {}).get(
                    "operator"
                ) in ("Exists", "In") and k not in caps:
                    caps.append(k)
        params = spec.get("parameters") or {}
        if isinstance(params, dict):
            for key, into in (
                ("allowed", allowed),
                ("domains", domains),
                ("required", required),
            ):
                v = params.get(key)
                if isinstance(v, list):
                    into.extend(x for x in v if isinstance(x, str))
    return {
        "tools": tools,
        "caps": caps,
        "allowed": allowed,
        "domains": domains,
        "required": required,
    }


def synth_agent_reviews(
    constraints: Sequence[Dict[str, Any]],
    k: int = DEFAULT_K,
) -> List[Dict[str, Any]]:
    """Deterministic agent-action canaries (the agent.action target's
    counterpart of synth_reviews), normalized through
    AgentActionTarget.review_of — the exact serving shape. Three
    variants cycle: empty-arguments (trips required-argument shapes),
    compliant (signed skill, allow-listed values), and bad-values
    (denied command/host, unsigned skill, a `bad`-keyed skill digest so
    pinned-stub external-data lookups answer with an error)."""
    from ..agentaction import AgentActionTarget

    mined = _mine_agent(constraints)
    seed = _stable(
        "|".join(
            sorted(
                f'{c.get("kind", "")}/'
                f'{(c.get("metadata") or {}).get("name", "")}'
                for c in constraints
            )
        )
    )
    tools = mined["tools"] or ["canary.invoke"]
    target = AgentActionTarget()
    reviews: List[Dict[str, Any]] = []
    for i in range(max(1, int(k))):
        compliant = i % 3 == 1
        if compliant:
            arguments: Dict[str, Any] = {
                r: "integrity-canary" for r in mined["required"]
            }
            arguments["command"] = (
                mined["allowed"][0] if mined["allowed"] else "true"
            )
            arguments["host"] = (
                mined["domains"][0] if mined["domains"]
                else "canary.example.com"
            )
            skill = {
                "name": "integrity-canary-skill",
                "signed": True,
                "publisher": "first-party",
                "digest": f"pinned-canary-{seed:08x}",
            }
        elif i % 3 == 2:
            # bad-values variant: present but denied everywhere
            arguments = {r: "integrity-canary" for r in mined["required"]}
            arguments["command"] = "integrity-canary-denied"
            arguments["host"] = "canary.invalid"
            skill = {
                "name": "integrity-canary-skill",
                "signed": False,
                "publisher": "integrity-canary",
                "digest": f"bad-canary-{seed:08x}",
            }
        else:
            # empty-arguments variant: trips required-argument shapes
            arguments = {}
            skill = {
                "name": "integrity-canary-skill",
                "signed": False,
                "publisher": "integrity-canary",
                "digest": f"bad-canary-{seed:08x}",
            }
        record = {
            "id": f"integrity-canary-{seed:08x}-{i}",
            "agent": "system:integrity-canary",
            "session": "integrity-canary",
            "tool": tools[i % len(tools)],
            "arguments": arguments,
            "capabilities": list(mined["caps"]) or ["integrity-canary"],
            "skill": skill,
        }
        reviews.append(target.review_of(record))
    return reviews


def result_digest(results: Optional[Sequence[Any]]) -> str:
    """Order-insensitive digest of one review's verdict set: sorted
    (kind, constraint name, message, enforcement action) tuples. Merge
    order differs between the monolithic and partitioned paths
    (`merge_partition_results` re-sorts), so the digest must not."""
    rows = []
    for r in results or ():
        c = getattr(r, "constraint", None) or {}
        meta = c.get("metadata") or {} if isinstance(c, dict) else {}
        kind = c.get("kind", "") if isinstance(c, dict) else ""
        rows.append(
            (
                str(kind),
                str(meta.get("name", "")),
                str(getattr(r, "msg", "")),
                str(getattr(r, "enforcement_action", "")),
            )
        )
    rows.sort()
    payload = json.dumps(rows, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def split_digests(split: Sequence[Sequence[Any]]) -> List[str]:
    """Per-review digests for a review-major result split."""
    return [result_digest(rs) for rs in split]
