"""Verdict-integrity plane: canary rows, sampled shadow oracle, and
silent-data-corruption quarantine (docs/robustness.md §Verdict
integrity)."""

from .canary import result_digest, split_digests, synth_reviews
from .plane import IntegrityPlane, shadow_sampled

__all__ = [
    "IntegrityPlane",
    "result_digest",
    "shadow_sampled",
    "split_digests",
    "synth_reviews",
]
