"""Host-platform environment control for the axon TPU tunnel.

The deployment image injects a TPU tunnel via sitecustomize on
PYTHONPATH (activated by PALLAS_AXON_POOL_IPS); when the tunnel is down,
ANY jax backend touch in an exposed process hangs indefinitely. Evidence
harnesses (bench.py, __graft_entry__.py) therefore make the platform
decision from the ENV ALONE and run CPU work in subprocesses scrubbed by
these helpers, which must stay importable without touching jax.

Keep both sides of the contract here: bench.py and __graft_entry__.py
both import this module, so an axon env-contract change (new activation
var, renamed site dir) lands in one place.
"""

import os

_SITE_MARKER = ".axon_site"
_ACTIVATION_VAR = "PALLAS_AXON_POOL_IPS"


def axon_requested(environ=os.environ) -> bool:
    """The env promises a TPU tunnel. Never probe devices to find out:
    a wedged tunnel hangs any backend touch."""
    return bool(environ.get(_ACTIVATION_VAR)) and "axon" in (
        environ.get("JAX_PLATFORMS", "")
    )


def scrub_axon_env(environ=None) -> dict:
    """A copy of `environ` in which the axon plugin can NEVER load: the
    sitecustomize no-ops without its activation var, and stripping the
    site dir from PYTHONPATH removes even the registration hook. Sets
    JAX_PLATFORMS=cpu so the child claims the CPU backend outright."""
    env = dict(os.environ if environ is None else environ)
    env.pop(_ACTIVATION_VAR, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and _SITE_MARKER not in p
    )
    return env


def claim_cpu_platform() -> None:
    """Claim the CPU backend at the jax-config level in THIS process,
    before any backend initializes. The env var alone is not enough when
    the axon sitecustomize already ran: it sets jax_platforms="axon,cpu"
    at the config level, which outranks JAX_PLATFORMS."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
