"""Metrics registry + Prometheus text exposition.

Counterpart of pkg/metrics/ (OpenCensus views -> Prometheus exporter on
:8888, exporter.go:26, prometheus_exporter.go:17-40) and the per-package
stats reporters (webhook/audit/controller stats_reporter.go files). The
metric names follow the reference's docs/Metrics.md catalogue:
request_count, request_duration_seconds, violations,
audit_duration_seconds, audit_last_run_time, constraints,
constraint_templates, sync, watch_manager_* — tagged with the same
label keys (admission_status, enforcement_action, status, ...).

Latency distributions (`*_seconds`) expose as REAL Prometheus
histograms — cumulative `_bucket{le=...}` series plus `_min`/`_max`
gauge companions — so p50/p99 are recoverable from /metrics
(docs/metrics.md). The full emitted-name set is contract-tested
against docs/metrics.md by tests/test_metrics_contract.py.
"""

from .registry import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    serve_metrics,
)
