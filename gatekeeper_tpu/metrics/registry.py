"""In-process metrics: counters, gauges, distributions; Prometheus text
format exposition over stdlib HTTP."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple


def _tag_key(tags: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in tags.items()))


@dataclass
class _Dist:
    count: int = 0
    total: float = 0.0
    minimum: float = float("inf")
    maximum: float = float("-inf")

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.minimum = min(self.minimum, v)
        self.maximum = max(self.maximum, v)


class MetricsRegistry:
    """Record-style API mirroring pkg/metrics/record.go: one call site
    per measurement, tags as keyword args."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._dists: Dict[Tuple[str, Tuple], _Dist] = {}

    # -- write ---------------------------------------------------------------

    def record(self, name: str, value: float = 1, **tags) -> None:
        """Add to a counter."""
        key = (name, _tag_key(tags))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def gauge(self, name: str, value: float, **tags) -> None:
        key = (name, _tag_key(tags))
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value: float, **tags) -> None:
        """Add a sample to a distribution (latency histograms)."""
        key = (name, _tag_key(tags))
        with self._lock:
            self._dists.setdefault(key, _Dist()).add(value)

    def timed(self, name: str, **tags):
        """Context manager: records elapsed seconds into `name`."""
        reg = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                reg.observe(name, time.perf_counter() - self.t0, **tags)
                return False

        return _Timer()

    # -- read ----------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": {
                    self._fmt(k): v for k, v in self._counters.items()
                },
                "gauges": {self._fmt(k): v for k, v in self._gauges.items()},
                "distributions": {
                    self._fmt(k): {
                        "count": d.count,
                        "sum": d.total,
                        "min": d.minimum if d.count else None,
                        "max": d.maximum if d.count else None,
                        "avg": d.total / d.count if d.count else None,
                    }
                    for k, d in self._dists.items()
                },
            }

    @staticmethod
    def _escape(v: str) -> str:
        """Prometheus exposition label-value escaping: backslash, double
        quote, and newline must be escaped or scrapers reject the page."""
        return (
            v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        )

    @classmethod
    def _fmt(cls, key: Tuple[str, Tuple]) -> str:
        name, tags = key
        if not tags:
            return name
        inner = ",".join(f'{k}="{cls._escape(v)}"' for k, v in tags)
        return f"{name}{{{inner}}}"

    def prometheus_text(self, prefix: str = "gatekeeper_") -> str:
        """Prometheus exposition format (prometheus_exporter.go's output
        namespace is "gatekeeper")."""
        lines = []
        typed = set()

        def _type(name: str, kind: str) -> None:
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {prefix}{name} {kind}")

        with self._lock:
            for (name, tags), v in sorted(self._counters.items()):
                _type(name, "counter")
                lines.append(f"{prefix}{self._fmt((name, tags))} {v}")
            for (name, tags), v in sorted(self._gauges.items()):
                _type(name, "gauge")
                lines.append(f"{prefix}{self._fmt((name, tags))} {v}")
            for (name, tags), d in sorted(self._dists.items()):
                _type(name, "summary")
                base = self._fmt((name, tags))
                if tags:
                    stem, rest = base.split("{", 1)
                    count_s = f"{stem}_count{{{rest}"
                    sum_s = f"{stem}_sum{{{rest}"
                else:
                    count_s, sum_s = f"{base}_count", f"{base}_sum"
                lines.append(f"{prefix}{count_s} {d.count}")
                lines.append(f"{prefix}{sum_s} {d.total}")
        return "\n".join(lines) + "\n"


def serve_metrics(
    registry: MetricsRegistry, port: int = 0, bind_addr: str = "127.0.0.1"
) -> ThreadingHTTPServer:
    """Serve /metrics (Prometheus text) on a background thread; returns
    the server (server_address[1] carries the bound port). The reference
    serves the same on --prometheus-port 8888; in-cluster runs bind
    0.0.0.0 so Prometheus can scrape the pod IP (run.py wires this)."""

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            payload = registry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer((bind_addr, port), _Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
