"""In-process metrics: counters, gauges, distributions with optional
histogram buckets; Prometheus text format exposition (with OpenMetrics
trace-id exemplars) over stdlib HTTP. Per-family series cardinality is
capped (`max_series_per_family`) so an unbounded label — pathological
constraint churn under `constraint_device_seconds_total{kind,name}` —
drops new series (counted in `metrics_dropped_series_total`) instead
of growing the registry without bound."""

from __future__ import annotations

import bisect
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

_log = logging.getLogger("gatekeeper_tpu.metrics")

# per-family live-series cap (env-overridable): the registry's defense
# against label-cardinality leaks — the soak leak sampler watches
# series_count(), this cap is what makes that curve provably bounded
DEFAULT_MAX_SERIES_PER_FAMILY = int(
    os.environ.get("GATEKEEPER_TPU_METRICS_MAX_SERIES", "512")
)

# the drop accounting must never itself be droppable (it is one series
# per capped family — bounded by the family-name universe, not labels)
_DROP_FAMILY = "metrics_dropped_series_total"

# Default latency buckets for *_seconds distributions (14 finite bounds
# + +Inf at exposition). Spans 100µs..30s: the fused admission path p50
# sits in the low milliseconds while a cold XLA compile is tens of
# seconds — the p99 cliff BENCH_r05 surfaced needs resolution at BOTH
# ends or the histogram quantiles saturate exactly where they matter.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _tag_key(tags: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in tags.items()))


@dataclass
class _Dist:
    count: int = 0
    total: float = 0.0
    minimum: float = float("inf")
    maximum: float = float("-inf")
    # histogram bounds (ascending) and per-bound NON-cumulative counts;
    # cumulation happens at exposition. None = plain summary.
    bounds: Optional[Tuple[float, ...]] = None
    bucket_counts: Optional[List[int]] = None
    # last (trace_id, value, wall ts) exemplar per bucket — the
    # OpenMetrics hook connecting a latency bucket to the trace that
    # landed in it (docs/observability.md §Exemplars)
    exemplars: Optional[List[Optional[Tuple[str, float, float]]]] = None

    def add(self, v: float, exemplar: Optional[str] = None) -> None:
        self.count += 1
        self.total += v
        self.minimum = min(self.minimum, v)
        self.maximum = max(self.maximum, v)
        if self.bounds is not None:
            # index of the first bound >= v (le semantics); v above the
            # last bound lands in the trailing +Inf slot
            idx = bisect.bisect_left(self.bounds, v)
            self.bucket_counts[idx] += 1
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = [None] * len(self.bucket_counts)
                self.exemplars[idx] = (str(exemplar), v, time.time())


class MetricsRegistry:
    """Record-style API mirroring pkg/metrics/record.go: one call site
    per measurement, tags as keyword args.

    Distributions whose name ends in `_seconds` get
    DEFAULT_LATENCY_BUCKETS automatically and expose as Prometheus
    histograms (`_bucket`/`_sum`/`_count` plus `_min`/`_max` gauges);
    override per metric with `set_buckets` (before the first sample) or
    pass `buckets=()` to keep a bucketless summary."""

    def __init__(self, max_series_per_family: Optional[int] = None):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._dists: Dict[Tuple[str, Tuple], _Dist] = {}
        self._bucket_conf: Dict[str, Tuple[float, ...]] = {}
        self._help: Dict[str, str] = {}
        # cardinality guard state: live series per family, dropped
        # series attempts per family, families already warned about
        self.max_series_per_family = (
            DEFAULT_MAX_SERIES_PER_FAMILY
            if max_series_per_family is None
            else int(max_series_per_family)
        )
        self._family_counts: Dict[str, int] = {}
        self._dropped: Dict[str, int] = {}
        self._warned: set = set()

    def _admit_locked(self, store: Dict, key: Tuple[str, Tuple]) -> bool:
        """Cardinality guard (caller holds the lock): an EXISTING
        series always updates; a NEW series is admitted only while its
        family is under the cap. The caller accounts a refusal via
        `_note_dropped` AFTER releasing the lock."""
        if key in store:
            return True
        name = key[0]
        if (
            name != _DROP_FAMILY
            and self._family_counts.get(name, 0)
            >= self.max_series_per_family
        ):
            return False
        self._family_counts[name] = self._family_counts.get(name, 0) + 1
        return True

    def _note_dropped(self, name: str) -> None:
        """Account one capped insert: the per-family drop counter —
        one bounded series per capped family, exempt from the cap
        itself — plus a once-per-family warning log."""
        warn = False
        with self._lock:
            self._dropped[name] = self._dropped.get(name, 0) + 1
            if name not in self._warned:
                self._warned.add(name)
                warn = True
        self.record("metrics_dropped_series_total", 1, family=name)
        if warn:
            _log.warning(
                "metric family %r hit the %d-series cardinality cap; "
                "dropping new label sets (see "
                "metrics_dropped_series_total)",
                name, self.max_series_per_family,
            )

    # -- configuration -------------------------------------------------------

    def set_buckets(self, name: str, bounds: Sequence[float]) -> None:
        """Histogram bounds for `name` (ascending; +Inf is implicit).
        An empty sequence forces plain summary exposition. Applies to
        samples recorded AFTER the call — configure at wiring time."""
        self._bucket_conf[name] = tuple(sorted(set(float(b) for b in bounds)))

    def describe(self, name: str, text: str) -> None:
        """# HELP text for `name` (one line; defaults to the name)."""
        self._help[name] = " ".join(str(text).split())

    def _bounds_for(self, name: str) -> Optional[Tuple[float, ...]]:
        conf = self._bucket_conf.get(name)
        if conf is not None:
            return conf or None
        if name.endswith("_seconds"):
            return DEFAULT_LATENCY_BUCKETS
        return None

    # -- write ---------------------------------------------------------------

    def record(self, name: str, value: float = 1, /, **tags) -> None:
        """Add to a counter. `name`/`value` are positional-only so a
        LABEL may itself be called `name` (the cost-attribution series
        tags constraints by kind + name)."""
        key = (name, _tag_key(tags))
        with self._lock:
            admitted = self._admit_locked(self._counters, key)
            if admitted:
                self._counters[key] = self._counters.get(key, 0) + value
        if not admitted:
            self._note_dropped(name)

    def gauge(self, name: str, value: float, /, **tags) -> None:
        key = (name, _tag_key(tags))
        with self._lock:
            admitted = self._admit_locked(self._gauges, key)
            if admitted:
                self._gauges[key] = value
        if not admitted:
            self._note_dropped(name)

    def observe(
        self, name: str, value: float, /, exemplar: Optional[str] = None,
        **tags,
    ) -> None:
        """Add a sample to a distribution (latency histograms).
        `exemplar` attaches a trace id to the sample's bucket, exposed
        in OpenMetrics exemplar syntax — the hop from a p99 bucket to
        the exact trace that landed in it."""
        key = (name, _tag_key(tags))
        with self._lock:
            d = self._dists.get(key)
            if d is None:
                if not self._admit_locked(self._dists, key):
                    d = None
                else:
                    bounds = self._bounds_for(name)
                    d = self._dists[key] = _Dist(
                        bounds=bounds,
                        bucket_counts=(
                            [0] * (len(bounds) + 1)
                            if bounds is not None
                            else None
                        ),
                    )
            if d is not None:
                d.add(value, exemplar=exemplar)
        if d is None:
            self._note_dropped(name)

    def timed(self, name: str, **tags):
        """Context manager: records elapsed seconds into `name`, tagged
        `status=ok|error` by whether the block raised (unless the
        caller already supplied a status tag) — error latency must be
        separable from success latency or timeouts hide inside p99."""
        reg = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, exc_type, *exc):
                out = tags
                if "status" not in tags:
                    out = dict(tags)
                    out["status"] = "error" if exc_type else "ok"
                reg.observe(name, time.perf_counter() - self.t0, **out)
                return False

        return _Timer()

    # -- read ----------------------------------------------------------------

    def series_count(self) -> int:
        """Total live series (counter + gauge + distribution label
        combinations). Label cardinality is the classic slow metrics
        leak; the soak sampler watches this number per window so an
        unbounded tag (a per-request id, a timestamp label) flags
        instead of OOMing a three-day-old pod."""
        with self._lock:
            return (
                len(self._counters) + len(self._gauges) + len(self._dists)
            )

    def dropped_series(self) -> Dict[str, int]:
        """{family -> new-series inserts dropped by the cardinality
        cap}. Non-empty means a label set outgrew
        `max_series_per_family` — the soak sampler records the total so
        a capped (bounded) registry is distinguishable from a leak."""
        with self._lock:
            return dict(self._dropped)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": {
                    self._fmt(k): v for k, v in self._counters.items()
                },
                "gauges": {self._fmt(k): v for k, v in self._gauges.items()},
                "distributions": {
                    self._fmt(k): {
                        "count": d.count,
                        "sum": d.total,
                        "min": d.minimum if d.count else None,
                        "max": d.maximum if d.count else None,
                        "avg": d.total / d.count if d.count else None,
                        **(
                            {
                                "buckets": [
                                    [b, c]
                                    for b, c in zip(
                                        list(d.bounds) + ["+Inf"],
                                        _cumulate(d.bucket_counts),
                                    )
                                ]
                            }
                            if d.bounds is not None
                            else {}
                        ),
                    }
                    for k, d in self._dists.items()
                },
            }

    @staticmethod
    def _escape(v: str) -> str:
        """Prometheus exposition label-value escaping: backslash, double
        quote, and newline must be escaped or scrapers reject the page."""
        return (
            v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        )

    @classmethod
    def _fmt(cls, key: Tuple[str, Tuple]) -> str:
        name, tags = key
        if not tags:
            return name
        inner = ",".join(f'{k}="{cls._escape(v)}"' for k, v in tags)
        return f"{name}{{{inner}}}"

    @staticmethod
    def _suffixed(base: str, suffix: str, extra_label: str = "") -> str:
        """Attach a series suffix to the metric NAME (before the label
        braces), optionally injecting one extra label (le for
        buckets)."""
        if "{" in base:
            stem, rest = base.split("{", 1)
            if extra_label:
                rest = f"{extra_label},{rest}"
            return f"{stem}{suffix}{{{rest}"
        if extra_label:
            return f"{base}{suffix}{{{extra_label}}}"
        return f"{base}{suffix}"

    def prometheus_text(self, prefix: str = "gatekeeper_") -> str:
        """Prometheus exposition format (prometheus_exporter.go's output
        namespace is "gatekeeper"). Every family gets `# HELP` and
        `# TYPE`; bucketed distributions expose as histograms,
        bucketless ones as summaries, and both carry `_min`/`_max`
        gauge companions (docs/metrics.md's distribution contract)."""
        lines = []
        typed = set()

        def _head(name: str, kind: str) -> None:
            if name not in typed:
                typed.add(name)
                help_text = self._help.get(
                    name, name.replace("_", " ")
                )
                lines.append(f"# HELP {prefix}{name} {help_text}")
                lines.append(f"# TYPE {prefix}{name} {kind}")

        def _fnum(v: float) -> str:
            return repr(v) if isinstance(v, float) else str(v)

        with self._lock:
            for (name, tags), v in sorted(self._counters.items()):
                _head(name, "counter")
                lines.append(f"{prefix}{self._fmt((name, tags))} {v}")
            for (name, tags), v in sorted(self._gauges.items()):
                _head(name, "gauge")
                lines.append(f"{prefix}{self._fmt((name, tags))} {v}")
            for (name, tags), d in sorted(self._dists.items()):
                kind = "histogram" if d.bounds is not None else "summary"
                _head(name, kind)
                base = self._fmt((name, tags))
                if d.bounds is not None:
                    cum = _cumulate(d.bucket_counts)

                    def _ex(idx: int) -> str:
                        # OpenMetrics exemplar: `# {trace_id="…"} v ts`
                        # appended to the bucket the sample landed in
                        if d.exemplars is None or d.exemplars[idx] is None:
                            return ""
                        tid, val, ts = d.exemplars[idx]
                        return (
                            f' # {{trace_id="{self._escape(tid)}"}}'
                            f" {_fnum(float(val))} {_fnum(float(ts))}"
                        )

                    for i, (bound, c) in enumerate(zip(d.bounds, cum)):
                        series = self._suffixed(
                            base, "_bucket",
                            f'le="{_fnum(float(bound))}"',
                        )
                        lines.append(f"{prefix}{series} {c}{_ex(i)}")
                    inf = self._suffixed(base, "_bucket", 'le="+Inf"')
                    lines.append(
                        f"{prefix}{inf} {d.count}{_ex(len(d.bounds))}"
                    )
                lines.append(
                    f"{prefix}{self._suffixed(base, '_count')} {d.count}"
                )
                lines.append(
                    f"{prefix}{self._suffixed(base, '_sum')} {d.total}"
                )
                if d.count:
                    # min/max companions (no native Prometheus slot in
                    # either histogram or summary): typed as gauges
                    for suffix, val in (
                        ("_min", d.minimum), ("_max", d.maximum)
                    ):
                        _head(f"{name}{suffix}", "gauge")
                        lines.append(
                            f"{prefix}{self._suffixed(base, suffix)} {val}"
                        )
        return "\n".join(lines) + "\n"


def _cumulate(counts: List[int]) -> List[int]:
    out, acc = [], 0
    for c in counts:
        acc += c
        out.append(acc)
    return out


def serve_metrics(
    registry: MetricsRegistry,
    port: int = 0,
    bind_addr: str = "127.0.0.1",
    tracer=None,
    attributor=None,
    recorder=None,
    decisions=None,
    partitions=None,
    slo=None,
    sched=None,
) -> ThreadingHTTPServer:
    """Serve /metrics (Prometheus text) on a background thread; returns
    the server (server_address[1] carries the bound port). The reference
    serves the same on --prometheus-port 8888; in-cluster runs bind
    0.0.0.0 so Prometheus can scrape the pod IP (run.py wires this).
    With a tracer, /debug/traces serves the trace ring (?trace_id= /
    ?limit= / ?format=otlp — docs/observability.md); an attributor adds
    /debug/costs (the top-K cost table), a flight recorder adds
    /debug/flightrecords, a decision log adds /debug/decisions, an SLO
    engine adds /debug/slo (live attainment/burn/saturation,
    docs/observability.md §SLO & saturation), a sched callable
    (returning per-plane scheduler snapshots) adds /debug/sched
    (admission scheduling: policy/overload/shed split + per-tenant
    quota table, docs/operations.md §Admission scheduling), and a
    partition dispatcher adds /debug/partitions (the live cost/locality
    plan composition) and /debug/programs (the compile plane: per-
    partition sub-program signatures + program-store stats,
    docs/compile.md) — the same debug surface the health plane
    serves."""

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            route = self.path.split("?")[0]
            if route == "/metrics":
                payload = registry.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4"
            elif tracer is not None and route == "/debug/traces":
                payload = export_traces(tracer, self.path).encode()
                ctype = "application/json"
            elif attributor is not None and route == "/debug/costs":
                payload = json.dumps(
                    attributor.table(_debug_costs_k(self.path))
                ).encode()
                ctype = "application/json"
            elif recorder is not None and route == "/debug/flightrecords":
                payload = recorder.export_json().encode()
                ctype = "application/json"
            elif decisions is not None and route == "/debug/decisions":
                payload = export_decisions(decisions, self.path).encode()
                ctype = (
                    "application/x-ndjson"
                    if "format=ndjson" in self.path
                    else "application/json"
                )
            elif slo is not None and route == "/debug/slo":
                from ..obs.slo import export_slo

                payload = export_slo(slo, self.path).encode()
                ctype = "application/json"
            elif sched is not None and route == "/debug/sched":
                from ..sched import export_sched

                payload = export_sched(
                    sched() if callable(sched) else sched, self.path
                ).encode()
                ctype = "application/json"
            elif partitions is not None and route == "/debug/partitions":
                payload = json.dumps(partitions.plan_table()).encode()
                ctype = "application/json"
            elif partitions is not None and route == "/debug/programs":
                payload = json.dumps(
                    partitions.programs_table()
                ).encode()
                ctype = "application/json"
            else:
                payload = b'{"error": "not found"}'
                self.send_response(404)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer((bind_addr, port), _Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd


def _traces_n(path: str) -> int:
    """?n=/?limit= from a /debug/traces request path (default 50,
    clamped). `limit` is the documented name; `n` stays accepted."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    raw = (q.get("limit") or q.get("n") or ["50"])[0]
    try:
        n = int(raw)
    except (ValueError, TypeError):
        n = 50
    return max(1, min(n, 1000))


def _debug_costs_k(path: str) -> Optional[int]:
    """?k= for /debug/costs (default 10; k=0 returns every row)."""
    from urllib.parse import parse_qs, urlparse

    try:
        k = int(parse_qs(urlparse(path).query).get("k", ["10"])[0])
    except (ValueError, TypeError):
        k = 10
    return None if k <= 0 else min(k, 10_000)


def export_decisions(decisions, path: str) -> str:
    """The one /debug/decisions renderer both HTTP planes (health +
    metrics) share: ?trace_id= / ?verdict= / ?plane= filter,
    ?limit=/?n= bounds the count, ?format=ndjson switches to
    one-record-per-line export (docs/observability.md §Decision log)."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)

    def _one(name):
        return (q.get(name) or [None])[0] or None

    query = {
        "trace_id": _one("trace_id"),
        "verdict": _one("verdict"),
        "plane": _one("plane"),
        "limit": _traces_n(path),
    }
    if (_one("format") or "").lower() == "ndjson":
        return decisions.export_ndjson(**query)
    return decisions.export_json(**query)


def export_traces(tracer, path: str) -> str:
    """The one /debug/traces renderer both HTTP planes (health +
    metrics) share: ?trace_id= narrows to one trace, ?limit=/?n=
    bounds the count, ?format=otlp switches to OTLP-JSON span export."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    trace_id = (q.get("trace_id") or [None])[0] or None
    n = _traces_n(path)
    fmt = (q.get("format") or [""])[0].lower()
    if fmt == "otlp":
        return tracer.export_otlp(n=n, trace_id=trace_id)
    return tracer.export_json(n=n, trace_id=trace_id)
