"""Incremental compile plane: content-addressed program store with
fingerprint-gated load (docs/compile.md).

The store wraps the persistent XLA compile cache with provenance: every
artifact is content-addressed (sha256) and attested with the machine
fingerprint that produced it (platform + CPU feature set + jaxlib
version + device kind). A foreign or corrupt artifact is skipped and
counted (`program_store_rejected_total{reason}`), never handed to XLA —
the "could lead to execution errors such as SIGILL" class from sharing
one flat cache dir across heterogeneous node pools dies here, failing
closed to a recompile instead of a crash loop.
"""

from .store import (  # noqa: F401
    SCHEMA_VERSION,
    ProgramStore,
    machine_fingerprint,
    store_from_env,
)
