"""Content-addressed program store with fingerprint-gated load.

Layout under the store root (default: the legacy compile-cache dir, so
existing deployments upgrade in place)::

    <root>/
      artifacts/
        <sha256>.bin         # content-addressed XLA cache payload
        <sha256>.meta.json   # attestation: schema, fingerprint, sha256,
                             # original cache filename, jaxlib, created
      by-fingerprint/
        <fp12>/xla/          # the ONLY dir ever handed to XLA as
                             # jax_compilation_cache_dir; populated
                             # exclusively by `adopt()` from artifacts
                             # whose attested fingerprint matches THIS
                             # machine, plus XLA's own writes

`adopt()` is the gate: it walks `artifacts/`, verifies each payload's
sha256 against its attestation, and materializes only fingerprint-
matching artifacts into this machine's private XLA dir. Everything else
is rejected-and-counted (`program_store_rejected_total{reason}` with
reason ∈ fingerprint_mismatch | corrupt | schema | unattested) and never
reaches XLA's deserializer. Legacy flat cache files sitting at the store
root (the pre-provenance layout that produced the SIGILL warnings in
MULTICHIP_r05) count as `unattested`.

`attest()` is the reverse edge: after a compile lands new entries in the
XLA dir, each is copied into `artifacts/` under its content hash with a
fingerprint attestation, making it loadable by identical machines and
rejectable by everyone else.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import platform
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Union

_log = logging.getLogger("gatekeeper_tpu.compile")

SCHEMA_VERSION = 1

_META_SUFFIX = ".meta.json"
_PAYLOAD_SUFFIX = ".bin"

# reasons are a closed set so the metric label can't explode and the
# docs/metrics.md row can enumerate them
REJECT_REASONS = ("fingerprint_mismatch", "corrupt", "schema", "unattested")


def _cpu_flags_digest() -> str:
    """Stable digest of the CPU feature set (the ISA surface an AOT
    artifact may depend on). /proc/cpuinfo `flags` on x86, `Features`
    on arm64; falls back to the machine string off-Linux."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                low = line.lower()
                if low.startswith("flags") or low.startswith("features"):
                    flags = " ".join(
                        sorted(set(line.split(":", 1)[1].split()))
                    )
                    break
    except OSError:
        pass
    if not flags:
        flags = platform.processor() or platform.machine() or "unknown"
    return hashlib.sha256(flags.encode()).hexdigest()[:16]


def machine_fingerprint(probe_device: bool = True) -> Dict[str, str]:
    """Identity of the artifact-consuming machine: platform + CPU
    feature set + jaxlib version + accelerator kind, plus the sha256
    `digest` over all components. `probe_device=False` skips the JAX
    device probe (it can trigger backend init) for device-free tests."""
    comp: Dict[str, str] = {
        "platform": f"{platform.system()}-{platform.machine()}",
        "cpu_flags": _cpu_flags_digest(),
        "jaxlib": "none",
        "device_kind": "none",
    }
    try:
        import jaxlib  # type: ignore

        comp["jaxlib"] = str(getattr(jaxlib, "__version__", "unknown"))
    except Exception:
        pass
    if probe_device:
        try:
            import jax

            devs = jax.devices()
            if devs:
                comp["device_kind"] = str(
                    getattr(devs[0], "device_kind", devs[0].platform)
                )
        except Exception:
            pass
    comp["digest"] = hashlib.sha256(
        json.dumps(
            {k: v for k, v in comp.items() if k != "digest"},
            sort_keys=True,
        ).encode()
    ).hexdigest()
    return comp


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ProgramStore:
    """Fingerprint-gated wrapper around the persistent compile cache.

    Thread-safe; one instance per process (the driver holds it). The
    `fingerprint` parameter accepts a full component dict or a bare
    digest string — the latter is the device-free test override."""

    def __init__(
        self,
        root: str,
        metrics: Optional[Any] = None,
        fingerprint: Optional[Union[Dict[str, str], str]] = None,
        replica: Optional[str] = None,
        adopt: bool = True,
        probe_device: bool = True,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.metrics = metrics
        self.replica = replica
        fp = fingerprint
        if fp is None:
            fp = machine_fingerprint(probe_device=probe_device)
        if isinstance(fp, str):
            fp = {"digest": fp}
        self.fingerprint: Dict[str, str] = dict(fp)
        self.fp_digest: str = self.fingerprint["digest"]
        self.artifacts_dir = os.path.join(self.root, "artifacts")
        self.xla_cache_dir = os.path.join(
            self.root, "by-fingerprint", self.fp_digest[:12], "xla"
        )
        os.makedirs(self.artifacts_dir, exist_ok=True)
        os.makedirs(self.xla_cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        # cache filename -> sha256, for entries of THIS fingerprint
        # already attested (so attest() is incremental)
        self._attested: Dict[str, str] = {}
        self.hits = 0
        self.misses = 0
        self.saves = 0
        self.rejected: Dict[str, int] = {r: 0 for r in REJECT_REASONS}
        # last adopt() verdict per artifact, for /debug/programs and the
        # compile_storm flight record
        self._rows: List[Dict[str, Any]] = []
        if adopt:
            self.adopt()

    # ------------------------------------------------------------------
    # counters (one literal call site per metric — the metrics-contract
    # scan in tests/test_metrics_contract.py keys on these)

    def _note_hit(self) -> None:
        self.hits += 1
        if self.metrics is not None:
            self.metrics.record("program_store_hits_total", 1)

    def note_miss(self) -> None:
        """Called by the driver when a program had to be compiled (no
        adoptable artifact covered it)."""
        with self._lock:
            self.misses += 1
        if self.metrics is not None:
            self.metrics.record("program_store_misses_total", 1)

    def _note_save(self) -> None:
        self.saves += 1
        if self.metrics is not None:
            self.metrics.record("program_store_saves_total", 1)

    def _note_reject(self, reason: str) -> None:
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.record(
                "program_store_rejected_total", 1, reason=reason
            )

    def _note_entries(self, n: int) -> None:
        if self.metrics is not None:
            self.metrics.gauge("program_store_entries", n)

    # ------------------------------------------------------------------

    def adopt(self) -> Dict[str, int]:
        """Validate every stored artifact and materialize the ones
        attested for THIS machine into the private XLA cache dir.
        Returns {adopted, rejected} counts for this pass. Never raises:
        a broken artifact is a rejection, not an exception."""
        adopted = 0
        rejected = 0
        rows: List[Dict[str, Any]] = []
        with self._lock:
            try:
                names = sorted(os.listdir(self.artifacts_dir))
            except OSError:
                names = []
            metas = [n for n in names if n.endswith(_META_SUFFIX)]
            claimed = set()
            for meta_name in metas:
                meta_path = os.path.join(self.artifacts_dir, meta_name)
                sha_from_name = meta_name[: -len(_META_SUFFIX)]
                row: Dict[str, Any] = {
                    "artifact": sha_from_name[:12],
                    "status": "",
                    "reason": "",
                }
                verdict = self._validate_locked(
                    meta_path, sha_from_name, row
                )
                claimed.add(sha_from_name + _PAYLOAD_SUFFIX)
                if verdict is None:
                    rejected += 1
                else:
                    filename, payload = verdict
                    dst = os.path.join(self.xla_cache_dir, filename)
                    try:
                        if not os.path.exists(dst):
                            shutil.copyfile(payload, dst)
                        self._attested[filename] = sha_from_name
                        adopted += 1
                        row["status"] = "adopted"
                        self._note_hit()
                    except OSError as e:
                        rejected += 1
                        row["status"] = "rejected"
                        row["reason"] = "corrupt"
                        row["error"] = str(e)
                        self._note_reject("corrupt")
                rows.append(row)
            # payloads with no attestation never reach XLA
            for n in names:
                if n.endswith(_META_SUFFIX) or n in claimed:
                    continue
                rejected += 1
                rows.append({
                    "artifact": n[:12],
                    "status": "rejected",
                    "reason": "unattested",
                })
                self._note_reject("unattested")
            # legacy flat cache files at the root (the pre-provenance
            # layout): opaque XLA blobs of unknown origin — reject, do
            # not load, do not delete (an operator may want them back)
            try:
                for n in sorted(os.listdir(self.root)):
                    p = os.path.join(self.root, n)
                    if os.path.isdir(p):
                        continue
                    rejected += 1
                    rows.append({
                        "artifact": n[:24],
                        "status": "rejected",
                        "reason": "unattested",
                        "legacy": True,
                    })
                    self._note_reject("unattested")
            except OSError:
                pass
            self._rows = rows
            self._note_entries(len(self._attested))
        return {"adopted": adopted, "rejected": rejected}

    def _validate_locked(self, meta_path, sha_from_name, row):
        """One artifact through the gate. Returns (filename, payload
        path) when loadable on THIS machine, else None after counting
        the rejection and filling `row`."""
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if not isinstance(meta, dict):
                raise ValueError("meta is not an object")
        except Exception:
            row["status"] = "rejected"
            row["reason"] = "corrupt"
            self._note_reject("corrupt")
            return None
        if meta.get("schema") != SCHEMA_VERSION:
            row["status"] = "rejected"
            row["reason"] = "schema"
            self._note_reject("schema")
            return None
        sha = meta.get("sha256")
        filename = meta.get("filename")
        fp = meta.get("fingerprint")
        if (
            not isinstance(sha, str)
            or not isinstance(filename, str)
            or not isinstance(fp, str)
            or sha != sha_from_name
            or os.path.basename(filename) != filename
        ):
            row["status"] = "rejected"
            row["reason"] = "schema"
            self._note_reject("schema")
            return None
        row["filename"] = filename
        row["fingerprint"] = fp[:12]
        payload = os.path.join(
            self.artifacts_dir, sha + _PAYLOAD_SUFFIX
        )
        try:
            actual = _sha256_file(payload)
        except OSError:
            actual = ""
        if actual != sha:
            row["status"] = "rejected"
            row["reason"] = "corrupt"
            self._note_reject("corrupt")
            return None
        # the fingerprint gate proper: content is intact but was
        # compiled by a different machine class — never hand it to XLA
        if fp != self.fp_digest:
            row["status"] = "rejected"
            row["reason"] = "fingerprint_mismatch"
            self._note_reject("fingerprint_mismatch")
            return None
        return filename, payload

    def attest(self) -> int:
        """Content-address any new XLA cache entries this machine has
        produced and write their attestation. Returns the number of
        newly attested artifacts."""
        new = 0
        with self._lock:
            try:
                names = sorted(os.listdir(self.xla_cache_dir))
            except OSError:
                return 0
            for filename in names:
                if filename in self._attested:
                    continue
                src = os.path.join(self.xla_cache_dir, filename)
                if not os.path.isfile(src):
                    continue
                try:
                    sha = _sha256_file(src)
                    payload = os.path.join(
                        self.artifacts_dir, sha + _PAYLOAD_SUFFIX
                    )
                    if not os.path.exists(payload):
                        shutil.copyfile(src, payload)
                    meta = {
                        "schema": SCHEMA_VERSION,
                        "sha256": sha,
                        "filename": filename,
                        "fingerprint": self.fp_digest,
                        "jaxlib": self.fingerprint.get("jaxlib", "none"),
                        "created": time.time(),
                    }
                    tmp = os.path.join(
                        self.artifacts_dir,
                        f".{sha}{_META_SUFFIX}.tmp",
                    )
                    with open(tmp, "w") as f:
                        json.dump(meta, f, sort_keys=True)
                    os.replace(
                        tmp,
                        os.path.join(
                            self.artifacts_dir, sha + _META_SUFFIX
                        ),
                    )
                except OSError as e:
                    _log.warning(
                        "program store: attest failed for %s: %s",
                        filename, e,
                    )
                    continue
                self._attested[filename] = sha
                new += 1
                self._note_save()
            self._note_entries(len(self._attested))
        return new

    # ------------------------------------------------------------------
    # introspection (for /debug/programs and the flight recorder)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "root": self.root,
                "xla_cache_dir": self.xla_cache_dir,
                "fingerprint": self.fp_digest,
                "fingerprint_components": {
                    k: v
                    for k, v in self.fingerprint.items()
                    if k != "digest"
                },
                "entries": len(self._attested),
                "hits": self.hits,
                "misses": self.misses,
                "saves": self.saves,
                "rejected": dict(self.rejected),
            }

    def table(self) -> List[Dict[str, Any]]:
        """Per-artifact adoption verdicts from the last adopt() pass."""
        with self._lock:
            return [dict(r) for r in self._rows]


def store_from_env(
    metrics: Optional[Any] = None,
    replica: Optional[str] = None,
) -> Optional[ProgramStore]:
    """Build the process store from the environment, honoring the same
    kill switch as the legacy cache block (NO_COMPILE_CACHE=1 -> None,
    which tests/conftest.py sets so tier-1 never touches disk)."""
    if os.environ.get("GATEKEEPER_TPU_NO_COMPILE_CACHE") == "1":
        return None
    root = os.environ.get(
        "GATEKEEPER_TPU_COMPILE_CACHE_DIR",
        os.path.join("~", ".cache", "gatekeeper_tpu", "xla"),
    )
    try:
        return ProgramStore(root, metrics=metrics, replica=replica)
    except OSError as e:
        _log.warning("program store unavailable at %s: %s", root, e)
        return None
