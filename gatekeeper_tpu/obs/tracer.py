"""In-process request tracing: spans, traces, ring-buffer retention.

The enforcement plane must be able to account for every decision it
makes (EHV's runtime-monitor accounting, arxiv 2605.17909): a slow
AdmissionReview needs to name its cost center — batch queue wait vs
flatten/encode vs XLA compile vs device execution vs violation render.
This module is the lightweight, dependency-free tracer that carries
that attribution: OpenTelemetry's span model (trace_id / span_id /
parent links / attributes) without the SDK, exported as plain JSON at
`/debug/traces` and correlated into denial logs via `trace_id`
(`StructuredLogger.with_values`).

Design constraints that shaped it:
  * the hot path is the admission handler — span start/finish is a
    dict append under one lock, no I/O, no serialization;
  * requests cross threads (handler thread -> micro-batch worker ->
    back), so spans parent two ways: implicitly from a thread-local
    stack (nested `with` blocks on one thread), or explicitly from a
    `SpanContext` carried across the queue (`record_span`);
  * one fused batch dispatch serves many requests — the batcher
    records the SAME timing window as a span into every member
    request's trace, so each trace is self-contained;
  * retention is a bounded ring (completed traces) — tracing is always
    on and must never become the memory leak it exists to diagnose.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import re
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

# W3C Trace Context (https://www.w3.org/TR/trace-context/): version 00,
# 32 lowercase-hex trace id, 16 lowercase-hex parent id, 2-hex flags.
# All-zero ids are explicitly invalid per spec.
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """The trace id from a W3C `traceparent` header, or None when the
    header is absent/malformed (an invalid header MUST be ignored per
    spec — the request then gets a derived or fresh trace id)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None or m.group(1) == "ff":
        return None
    tid = m.group(2)
    if tid == "0" * 32 or m.group(3) == "0" * 16:
        return None
    return tid


def derive_trace_id(seed: Optional[str]) -> Optional[str]:
    """Deterministic 32-hex trace id from a stable request identity
    (the admission UID): a caller that sent no `traceparent` still gets
    a trace id any hop holding the same UID can reconstruct."""
    if not seed:
        return None
    return hashlib.sha256(str(seed).encode()).hexdigest()[:32]


def format_traceparent(trace_id: str, span_seed: str = "") -> str:
    """A well-formed `traceparent` response header for `trace_id`
    (padded/derived to 32 hex); the parent-id half is derived — this
    engine's span ids are not 16-hex, and the header only needs to name
    the trace, not a resumable span."""
    tid = _otlp_id(trace_id, 32)
    sid = hashlib.sha256((trace_id + span_seed).encode()).hexdigest()[:16]
    return f"00-{tid}-{sid}-01"


def _otlp_id(raw: Optional[str], width: int) -> str:
    """Map an internal id to the fixed-width lowercase-hex form OTLP
    requires: ids that are already hex (W3C-ingested trace ids) pass
    through zero-padded; everything else hashes deterministically."""
    if not raw:
        return "0" * width
    s = str(raw).lower()
    if re.fullmatch(r"[0-9a-f]+", s) and len(s) <= width:
        return s.zfill(width)
    return hashlib.sha256(s.encode()).hexdigest()[:width]


class SpanContext(NamedTuple):
    """The cross-thread handle: enough to parent a child span."""

    trace_id: str
    span_id: str


class Span:
    """One timed operation. Use as a context manager (enter starts the
    clock and pushes onto the thread-local stack; exit records) or let
    the tracer record pre-timed windows via `record_span`."""

    __slots__ = (
        "tracer", "name", "trace_id", "span_id", "parent_id",
        "start", "_t0", "end", "attrs", "status",
    )

    def __init__(self, tracer, name, trace_id, span_id, parent_id, attrs):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = attrs
        self.start: float = 0.0
        self._t0: float = 0.0
        self.end: float = 0.0
        self.status = "ok"

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, **kv) -> None:
        self.attrs.update(kv)

    def __enter__(self) -> "Span":
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # wall start + perf duration: readable timestamps, monotonic
        # durations (time.time can step under NTP)
        self.end = self.start + (time.perf_counter() - self._t0)
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", str(exc))
        self.tracer._pop(self)
        self.tracer._finish(self)
        return False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_ms": round(max(0.0, self.end - self.start) * 1e3, 3),
            "status": self.status,
            "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """Stand-in when no tracer is wired: every operation is free."""

    context = None
    trace_id = None

    def set_attr(self, **kv) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def start_span(tracer: Optional["Tracer"], name: str, parent=None, **attrs):
    """Tracer-optional span start: call sites stay unconditional
    (`with start_span(self.tracer, "dispatch") as sp:`) whether or not
    tracing is wired."""
    if tracer is None:
        return NOOP_SPAN
    return tracer.start_span(name, parent=parent, **attrs)


class Tracer:
    """Span recorder with bounded retention.

    Completed traces (every span finished) move to a ring buffer of
    `max_traces`; a trace is also force-completed at
    `max_spans_per_trace` so a leaked open span cannot pin memory.
    """

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 256):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        # trace_id -> {"spans": [dict], "open": int}
        self._active: Dict[str, Dict[str, Any]] = {}
        self._ring: List[Dict[str, Any]] = []
        self._ids = itertools.count(1)
        self._local = threading.local()

    # -- id allocation -------------------------------------------------------

    def _new_id(self, kind: str) -> str:
        return f"{kind}{next(self._ids):08x}"

    # -- thread-local current-span stack -------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    # -- span lifecycle ------------------------------------------------------

    def start_span(self, name: str, parent=None, trace_id=None,
                   **attrs) -> Span:
        """New span. Parent resolution: explicit `parent`
        (Span/SpanContext) wins; else the calling thread's innermost
        open span; else a fresh trace root."""
        ctx = getattr(parent, "context", parent)
        if ctx is None:
            cur = self.current()
            ctx = cur.context if cur is not None else None
        if ctx is not None:
            tid, parent_id = ctx.trace_id, ctx.span_id
        else:
            tid, parent_id = trace_id or self._new_id("t"), None
        span = Span(self, name, tid, self._new_id("s"), parent_id, attrs)
        with self._lock:
            ent = self._active.setdefault(tid, {"spans": [], "open": 0})
            ent["open"] += 1
        return span

    def record_span(self, name: str, start: float, end: float,
                    parent=None, trace_id=None, status: str = "ok",
                    **attrs) -> Optional[SpanContext]:
        """Record an already-timed window (the cross-thread form: the
        batch worker stamps queue-wait/dispatch spans into each member
        request's trace). Returns the new span's context so callers can
        hang children off it."""
        ctx = getattr(parent, "context", parent)
        if ctx is not None:
            tid, parent_id = ctx.trace_id, ctx.span_id
        elif trace_id is not None:
            tid, parent_id = trace_id, None
        else:
            return None
        span = Span(self, name, tid, self._new_id("s"), parent_id, attrs)
        span.start, span.end, span.status = start, end, status
        # registered=False: this span never incremented the trace's
        # open count (start_span does), so it must not decrement it —
        # otherwise a worker stamping batch spans into a request trace
        # would flush the trace out from under its still-open root
        self._finish(span, registered=False)
        return span.context

    def record_window(self, name: str, wall_start: float,
                      perf_start: float, parent=None, trace_id=None,
                      status: str = "ok", **attrs) -> Optional[SpanContext]:
        """record_span for callers holding a (wall, perf_counter) start
        pair: the end stamp is wall_start + the PERF-measured elapsed
        time, so the span's duration is monotonic (an NTP step between
        the two reads cannot stretch or invert it) while its timestamps
        stay wall-readable — the same hybrid Span.__exit__ uses."""
        end = wall_start + (time.perf_counter() - perf_start)
        return self.record_span(
            name, wall_start, end, parent=parent, trace_id=trace_id,
            status=status, **attrs,
        )

    def _finish(self, span: Span, registered: bool = True) -> None:
        with self._lock:
            ent = self._active.get(span.trace_id)
            if ent is None:
                # late span on a flushed trace (out-of-order finish):
                # attach if the trace is still in the ring
                for tr in reversed(self._ring):
                    if tr["trace_id"] == span.trace_id:
                        if len(tr["spans"]) < self.max_spans_per_trace:
                            tr["spans"].append(span.to_dict())
                        return
                # unknown trace id: a standalone recorded span becomes
                # its own one-shot trace
                ent = self._active.setdefault(
                    span.trace_id, {"spans": [], "open": 1}
                )
                registered = True
            if len(ent["spans"]) < self.max_spans_per_trace:
                ent["spans"].append(span.to_dict())
            if registered:
                ent["open"] = max(0, ent["open"] - 1)
            if ent["open"] == 0 or (
                len(ent["spans"]) >= self.max_spans_per_trace
            ):
                self._flush_locked(span.trace_id, ent)

    def _flush_locked(self, trace_id: str, ent: Dict[str, Any]) -> None:
        self._active.pop(trace_id, None)
        if not ent["spans"]:
            return
        self._ring.append({"trace_id": trace_id, "spans": ent["spans"]})
        if len(self._ring) > self.max_traces:
            del self._ring[: len(self._ring) - self.max_traces]

    # -- read ----------------------------------------------------------------

    def recent(self, n: int = 50) -> List[Dict[str, Any]]:
        """Most-recent completed traces, newest first."""
        with self._lock:
            return [
                {"trace_id": t["trace_id"], "spans": list(t["spans"])}
                for t in self._ring[-n:][::-1]
            ]

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for t in reversed(self._ring):
                if t["trace_id"] == trace_id:
                    return {"trace_id": trace_id, "spans": list(t["spans"])}
        return None

    def export_json(self, n: int = 50, trace_id: Optional[str] = None) -> str:
        """JSON export of the ring; `trace_id` narrows to one trace
        (empty list when it is not retained) — the `/debug/traces?
        trace_id=` lookup both HTTP planes serve."""
        if trace_id is not None:
            t = self.get(trace_id)
            return json.dumps({"traces": [t] if t is not None else []})
        return json.dumps({"traces": self.recent(n)})

    def export_otlp(self, n: int = 50, trace_id: Optional[str] = None) -> str:
        """OTLP-JSON span export (`/debug/traces?format=otlp`): the ring
        rendered as one resourceSpans/scopeSpans document an OTLP
        collector's JSON receiver ingests directly. Internal ids map to
        the 128/64-bit hex forms OTLP requires (W3C-ingested trace ids
        pass through unchanged); span attrs become stringValue
        attributes."""
        if trace_id is not None:
            t = self.get(trace_id)
            traces = [t] if t is not None else []
        else:
            traces = self.recent(n)
        spans = []
        for tr in traces:
            tid = _otlp_id(tr["trace_id"], 32)
            for sp in tr.get("spans", []):
                spans.append({
                    "traceId": tid,
                    "spanId": _otlp_id(sp.get("span_id"), 16),
                    "parentSpanId": (
                        _otlp_id(sp["parent_id"], 16)
                        if sp.get("parent_id")
                        else ""
                    ),
                    "name": sp.get("name", ""),
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(
                        int(sp.get("start", 0.0) * 1e9)
                    ),
                    "endTimeUnixNano": str(int(sp.get("end", 0.0) * 1e9)),
                    "status": {
                        "code": 2 if sp.get("status") == "error" else 1
                    },
                    "attributes": [
                        {
                            "key": str(k),
                            "value": {"stringValue": str(v)},
                        }
                        for k, v in (sp.get("attrs") or {}).items()
                    ],
                })
        return json.dumps({
            "resourceSpans": [{
                "resource": {
                    "attributes": [{
                        "key": "service.name",
                        "value": {"stringValue": "gatekeeper-tpu"},
                    }],
                },
                "scopeSpans": [{
                    "scope": {"name": "gatekeeper_tpu.obs"},
                    "spans": spans,
                }],
            }],
        })

    def size(self) -> Dict[str, int]:
        """Retention sizes (the soak leak sampler's view): completed
        traces in the ring, still-active traces, and total retained
        spans. The ring is bounded by construction — this exists so a
        soak can PROVE it, not assume it."""
        with self._lock:
            return {
                "ring": len(self._ring),
                "active": len(self._active),
                "spans": sum(len(t["spans"]) for t in self._ring)
                + sum(len(e["spans"]) for e in self._active.values()),
            }

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._active = {}


def span_breakdown(traces: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Aggregate span durations by name across traces: count / p50 /
    p99 / max in milliseconds. bench_webhook uses this to turn the raw
    trace ring into the per-cost-center table that explains a p99
    cliff."""
    by_name: Dict[str, List[float]] = {}
    for tr in traces:
        for sp in tr.get("spans", []):
            by_name.setdefault(sp["name"], []).append(sp["duration_ms"])

    def pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
        return sorted_vals[idx]

    out: Dict[str, Dict[str, float]] = {}
    for name, vals in sorted(by_name.items()):
        vals.sort()
        out[name] = {
            "count": len(vals),
            "p50_ms": round(pct(vals, 0.50), 3),
            "p99_ms": round(pct(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3),
        }
    return out
