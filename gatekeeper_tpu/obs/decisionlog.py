"""Per-admission decision log: the "why" record plane.

PR 10 made the plane's COST diagnosable (traces, per-constraint device
seconds, flight records); this module makes each individual VERDICT
diagnosable after the fact. Every handled admission — validation,
mutation, agent review, audit violation — can leave one bounded
`DecisionRecord` answering:

  * **what happened** — allow/deny/error/unavailable, response code,
    the violated constraint keys + messages;
  * **how it was served** — the dispatch route (fused / interp / host /
    degraded), and for partitioned dispatch the exact partition set
    dispatched vs mask-skipped with the rows_dispatched/rows_total
    pruning facts from `partition_match_mask` (ROADMAP item 1's
    dispatched-rows/total-rows instrument);
  * **what it consumed** — render-cache hits, external-data fetches,
    mutation fixpoint iterations, the batch-apportioned device-time
    share, and the deadline slack left at answer time;
  * **who asked** — tenant identity (namespace / username for K8s,
    agent + session for tool calls), joined to everything else by the
    request's trace id (`/debug/traces?trace_id=`).

Retention policy (head+error sampling): denials, errors, sheds,
degraded/host routes, and the slow tail are ALWAYS kept; plain allows
are sampled at 1-in-`allow_sample_n`, deterministically by trace id so
replays and multi-replica views agree on which allows survive. The
ring is bounded (`max_records`) with an optional bounded disk spool
(`dir=` / `GATEKEEPER_TPU_DECISION_DIR`, mirroring the flight
recorder), and appends are token-bucket rate-limited so a shed burst
cannot turn the observability plane itself into the leak
(`decisions_dropped_total`). Served at `/debug/decisions`
(?trace_id= / ?verdict= / ?plane= / ?limit= / ?format=ndjson) on both
HTTP planes. docs/observability.md §Decision log.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional

__all__ = [
    "DECISION_SCHEMA_FIELDS",
    "DecisionLog",
    "check_decision_schema",
]

DEFAULT_MAX_RECORDS = 1024
DEFAULT_ALLOW_SAMPLE_N = 16
DEFAULT_SLOW_MS = 250.0
DEFAULT_MAX_PER_S = 200.0

# fields every DecisionRecord carries (the schema contract test pins
# this against what record() actually builds)
DECISION_SCHEMA_FIELDS = (
    "id", "ts", "plane", "verdict", "code", "trace_id", "route",
    "tenant", "violations", "duration_ms", "sampled",
)

# verdicts that are never sampled out (the "error" half of head+error
# sampling); routes that force retention are judged separately
_ALWAYS_KEEP_VERDICTS = frozenset(
    # verdict_divergence: the shadow oracle's SDC evidence record —
    # far too rare and too important to lose to allow-sampling
    ("deny", "dryrun", "error", "shed", "unavailable",
     "verdict_divergence")
)
_ALWAYS_KEEP_ROUTES = frozenset(
    ("host", "degraded", "fallback", "unavailable")
)


def check_decision_schema(record: Dict[str, Any]) -> List[str]:
    """Missing-field list for one decision record (empty = valid)."""
    return [f for f in DECISION_SCHEMA_FIELDS if f not in record]


class _TokenBucket:
    """Steady-rate admission for ring appends: `rate` tokens/second,
    burst up to `burst`. Callers under a lock of their own — this one
    is self-locking and O(1) per call."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def allow(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True  # 0/negative disables the limiter
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


def _keep_hash(trace_id: str) -> int:
    """Deterministic sampling hash: stable across processes and runs
    (Python's str hash is salted per process) so every replica keeps
    the SAME 1-in-N allow set for a given trace id."""
    return zlib.crc32(trace_id.encode())


class DecisionLog:
    """Bounded per-admission decision ring + the dispatch-fact side
    channel the micro-batchers feed (`note_dispatch`, keyed by trace
    id) so the handler-level `record()` can explain the route its
    request actually took."""

    def __init__(
        self,
        metrics=None,
        replica: Optional[str] = None,
        max_records: int = DEFAULT_MAX_RECORDS,
        dir: Optional[str] = None,
        # head+error sampling: keep 1 in N plain allows (1 = keep all,
        # 0/None = drop all unforced allows)
        allow_sample_n: Optional[int] = DEFAULT_ALLOW_SAMPLE_N,
        # always keep requests slower than this (the slow tail is
        # exactly what a postmortem wants explained)
        slow_ms: float = DEFAULT_SLOW_MS,
        # token-bucket append ceiling (records/second) shared by the
        # decision ring and the sibling denial logs it gates
        max_per_s: float = DEFAULT_MAX_PER_S,
        # bounded on-disk NDJSON spool (one file, rewritten on
        # rotation) — None/"" = memory only
        clock=time.monotonic,
        # optional SloEngine (obs/slo.py): every record_decision call
        # feeds it BEFORE sampling/rate-gating, so the streaming SLO
        # estimator sees the full stream the ring only samples; also
        # settable post-construction (`log.slo = engine`)
        slo=None,
    ):
        self.metrics = metrics
        self.slo = slo
        self.replica = replica
        self.max_records = max(1, int(max_records))
        self.dir = dir if dir is not None else os.environ.get(
            "GATEKEEPER_TPU_DECISION_DIR"
        ) or None
        self.allow_sample_n = (
            int(allow_sample_n) if allow_sample_n else 0
        )
        self.slow_ms = float(slow_ms)
        self._clock = clock
        self._gate = _TokenBucket(max_per_s, clock=clock)
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        # trace_id -> dispatch facts stashed by the batch worker,
        # popped by record(); bounded so an orphaned fact (a request
        # whose handler died before recording) cannot accumulate
        self._facts: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._facts_max = max(64, self.max_records * 4)
        self._seq = 0
        self._spool_count = 0
        # accounting (snapshot/readyz/soak sampler)
        self.recorded = 0
        self.sampled_out = 0
        self.dropped = 0
        self.denial_log_dropped = 0
        self.route_counts: Dict[str, int] = {}
        # exact per-tenant verdict accounting over the FULL decision
        # stream (counted before sampling/rate gates — the ring only
        # samples, but attainment splits must be exact): the soak
        # reporter's per-tenant SLO-attainment source
        self._tenant_counts: Dict[str, Dict[str, int]] = {}
        self._tenant_counts_max = 64

    # -- dispatch facts (the batch worker's half) -----------------------------

    def note_dispatch(self, trace_id: Optional[str], **facts) -> None:
        """Stash one request's dispatch facts (route, partition set,
        rows, fetch/cache counts, device share) under its trace id for
        the handler-level record() to claim. Non-blocking, bounded,
        and merge-on-repeat — the mutate plane adds fixpoint facts to
        the same trace the validation dispatch already explained."""
        if not trace_id:
            return
        with self._lock:
            cur = self._facts.get(trace_id)
            if cur is None:
                while len(self._facts) >= self._facts_max:
                    self._facts.popitem(last=False)
                self._facts[trace_id] = dict(facts)
            else:
                cur.update(facts)
                self._facts.move_to_end(trace_id)
        # the batch-apportioned device share doubles as the SLO
        # engine's cost sample (saturation/headroom EWMA)
        share = facts.get("device_seconds_share")
        if share is not None and self.slo is not None:
            try:
                self.slo.note_cost(float(share), rows=1)
            except (TypeError, ValueError):
                pass

    def _pop_facts(self, trace_id: Optional[str]) -> Dict[str, Any]:
        if not trace_id:
            return {}
        with self._lock:
            return self._facts.pop(trace_id, None) or {}

    # -- sampling -------------------------------------------------------------

    def _keep_allow(self, trace_id: Optional[str]) -> bool:
        n = self.allow_sample_n
        if n <= 0:
            return False
        if n == 1:
            return True
        if trace_id:
            return _keep_hash(trace_id) % n == 0
        # no trace id: deterministic round-robin on the sequence
        with self._lock:
            seq = self._seq
        return seq % n == 0

    # -- the sibling denial-log gate ------------------------------------------

    def allow_denial_append(self, plane: str = "validation") -> bool:
        """Rate gate for the handlers' denial-log rings: same bucket as
        the decision ring, so a shed/deny storm is bounded across BOTH
        obs sinks (the satellite contract); refusals are counted."""
        if self._gate.allow():
            return True
        self.denial_log_dropped += 1
        if self.metrics is not None:
            self.metrics.record(
                "decisions_dropped_total", 1,
                plane=plane, reason="denial_log_rate",
            )
        return False

    # -- write ----------------------------------------------------------------

    def record_decision(
        self,
        plane: str,
        verdict: str,
        code: int = 200,
        trace_id: Optional[str] = None,
        duration_ms: Optional[float] = None,
        tenant: Optional[Dict[str, Any]] = None,
        violations: Optional[List[Dict[str, Any]]] = None,
        message: str = "",
        deadline_slack_ms: Optional[float] = None,
        **extra,
    ) -> Optional[Dict[str, Any]]:
        """Build + retain one decision record. Returns the record, or
        None when sampling dropped it (plain allow outside the 1-in-N
        head) or the rate gate refused it (burst overload — counted in
        `decisions_dropped_total`). Never raises: the admission path
        calls this inline and a broken field must cost a record, not a
        request."""
        self._observe_slo(
            plane, verdict, duration_ms, deadline_slack_ms, tenant
        )
        self._note_tenant(
            plane, verdict, duration_ms, deadline_slack_ms, tenant
        )
        try:
            return self._record(
                plane, verdict, code, trace_id, duration_ms, tenant,
                violations, message, deadline_slack_ms, extra,
            )
        except Exception:
            return None

    def _observe_slo(
        self, plane, verdict, duration_ms, deadline_slack_ms, tenant,
    ) -> None:
        """The live-SLO seam: runs for EVERY decision, before the
        sampling and rate gates below (the estimator must see the full
        stream), and stamps the `admission_deadline_slack_seconds`
        histogram at the same spot that stamps `deadline_slack_ms`
        into the record. Fully defensive — observability feeds must
        never cost a request."""
        try:
            if self.metrics is not None and deadline_slack_ms is not None:
                # negative slack (deadline already blown) lands in the
                # first bucket, which is exactly the bucket to alarm on
                self.metrics.observe(
                    "admission_deadline_slack_seconds",
                    deadline_slack_ms / 1e3,
                    plane=plane,
                )
            slo = self.slo
            if slo is None:
                return
            shed = verdict in ("shed", "unavailable")
            duration_s = (
                duration_ms / 1e3 if duration_ms is not None else None
            )
            if shed or verdict == "error":
                ok = False
            else:
                # deny IS ok — the SLO is about answering in time, not
                # admitting. Judge vs the target's own deadline when
                # configured (the soak contract), else vs the slack the
                # handler computed from its request timeout.
                deadline = getattr(slo.target, "deadline_s", None)
                if deadline is not None and duration_s is not None:
                    ok = duration_s <= deadline
                elif deadline_slack_ms is not None:
                    ok = deadline_slack_ms >= 0.0
                else:
                    ok = True
            slo.observe(
                plane, ok,
                duration_s=duration_s, shed=shed, tenant=tenant,
            )
        except Exception:
            pass

    @staticmethod
    def _tenant_label(plane: str, tenant) -> Optional[str]:
        """`plane/name` identity matching the SLO engine's tenant key
        convention (namespace or agent or username)."""
        if not tenant:
            return None
        if isinstance(tenant, dict):
            name = str(
                tenant.get("namespace") or tenant.get("agent")
                or tenant.get("username") or ""
            )
        else:
            name = str(tenant)
        return f"{plane}/{name}" if name else None

    def _note_tenant(
        self, plane, verdict, duration_ms, deadline_slack_ms, tenant,
    ) -> None:
        """Exact per-tenant ok/miss/shed counters over the full stream;
        ok is judged the same way `_observe_slo` judges it (the SLO
        target deadline when configured, else the handler slack)."""
        try:
            key = self._tenant_label(plane, tenant)
            if key is None:
                return
            shed = verdict in ("shed", "unavailable")
            if shed or verdict == "error":
                ok = False
            else:
                slo = self.slo
                deadline = (
                    getattr(slo.target, "deadline_s", None)
                    if slo is not None else None
                )
                if deadline is not None and duration_ms is not None:
                    ok = duration_ms / 1e3 <= deadline
                elif deadline_slack_ms is not None:
                    ok = deadline_slack_ms >= 0.0
                else:
                    ok = True
            with self._lock:
                st = self._tenant_counts.get(key)
                if st is None:
                    if len(self._tenant_counts) >= self._tenant_counts_max:
                        key = f"{plane}/(other)"
                        st = self._tenant_counts.get(key)
                    if st is None:
                        st = self._tenant_counts[key] = {
                            "count": 0, "ok": 0, "miss": 0, "shed": 0,
                        }
                st["count"] += 1
                if shed:
                    st["shed"] += 1
                elif ok:
                    st["ok"] += 1
                else:
                    st["miss"] += 1
        except Exception:
            pass

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant attainment/shed split read straight from the
        decision stream (exact, not sampled): `{plane/name: {count, ok,
        miss, shed, attainment}}` — the soak reporter's headline for
        the multi-tenant overload scenario."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for key, st in sorted(self._tenant_counts.items()):
                row = dict(st)
                row["attainment"] = (
                    round(st["ok"] / st["count"], 6) if st["count"] else None
                )
                out[key] = row
            return out

    def _record(
        self, plane, verdict, code, trace_id, duration_ms, tenant,
        violations, message, deadline_slack_ms, extra,
    ) -> Optional[Dict[str, Any]]:
        facts = self._pop_facts(trace_id)
        route = str(facts.get("route") or extra.pop("route", "") or "")
        slow = (
            duration_ms is not None and duration_ms >= self.slow_ms
        )
        forced = (
            verdict in _ALWAYS_KEEP_VERDICTS
            or route in _ALWAYS_KEEP_ROUTES
            or slow
        )
        sampled = not forced
        if sampled and not self._keep_allow(trace_id):
            self.sampled_out += 1
            if self.metrics is not None:
                self.metrics.record(
                    "decisions_sampled_out_total", 1, plane=plane
                )
            return None
        if not self._gate.allow():
            self.dropped += 1
            if self.metrics is not None:
                self.metrics.record(
                    "decisions_dropped_total", 1,
                    plane=plane, reason="rate_limited",
                )
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
        record: Dict[str, Any] = {
            "id": f"d-{seq:06d}",
            "ts": time.time(),
            "t_monotonic": self._clock(),
            "plane": plane,
            "verdict": verdict,
            "code": int(code),
            "trace_id": trace_id,
            "route": route or None,
            "tenant": tenant or {},
            "violations": violations or [],
            "duration_ms": (
                round(duration_ms, 3) if duration_ms is not None else None
            ),
            "sampled": sampled,
        }
        if self.replica is not None:
            record["replica"] = self.replica
        if message:
            record["message"] = message[:512]
        if deadline_slack_ms is not None:
            record["deadline_slack_ms"] = round(deadline_slack_ms, 3)
        # dispatch facts (partitions dispatched/skipped, rows, cache/
        # fetch counts, device share, fixpoint iterations) ride as-is
        for k, v in facts.items():
            if k != "route":
                record[k] = v
        for k, v in extra.items():
            record[k] = v
        with self._lock:
            self._ring.append(record)
            if len(self._ring) > self.max_records:
                del self._ring[: len(self._ring) - self.max_records]
            self.recorded += 1
            rkey = route or "unknown"
            self.route_counts[rkey] = self.route_counts.get(rkey, 0) + 1
        if self.metrics is not None:
            self.metrics.record(
                "decisions_recorded_total", 1,
                plane=plane, verdict=verdict,
            )
        self._spool(record)
        return record

    def _spool(self, record: Dict[str, Any]) -> None:
        """Bounded disk mirror: NDJSON appends, file rewritten from the
        (bounded) ring every `max_records` appends so the spool can
        never outgrow ~2x the ring. Best-effort — a full disk must not
        take the admission path down."""
        if not self.dir:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, "decisions.ndjson")
            self._spool_count += 1
            if self._spool_count % self.max_records == 0:
                tmp = path + ".tmp"
                with self._lock:
                    ring = list(self._ring)
                with open(tmp, "w") as f:
                    for r in ring:
                        f.write(json.dumps(r) + "\n")
                os.replace(tmp, path)
            else:
                with open(path, "a") as f:
                    f.write(json.dumps(record) + "\n")
        except (OSError, ValueError, TypeError):
            pass

    # -- read -----------------------------------------------------------------

    def records(
        self,
        trace_id: Optional[str] = None,
        verdict: Optional[str] = None,
        plane: Optional[str] = None,
        limit: int = 100,
    ) -> List[Dict[str, Any]]:
        """Newest-first filtered view (the `/debug/decisions` body)."""
        with self._lock:
            rows = list(reversed(self._ring))
        if trace_id is not None:
            rows = [r for r in rows if r.get("trace_id") == trace_id]
        if verdict is not None:
            rows = [r for r in rows if r.get("verdict") == verdict]
        if plane is not None:
            rows = [r for r in rows if r.get("plane") == plane]
        return rows[: max(1, int(limit))]

    def recent_errors(
        self, window_s: float = 30.0, limit: int = 32
    ) -> List[Dict[str, Any]]:
        """Newest-first non-allow / degraded decisions within the last
        `window_s` — the trigger-window set a flight record embeds so a
        postmortem names the exact requests that failed."""
        horizon = self._clock() - window_s
        out = []
        with self._lock:
            for r in reversed(self._ring):
                if r.get("t_monotonic", 0.0) < horizon:
                    break
                if (
                    r.get("verdict") in _ALWAYS_KEEP_VERDICTS
                    or (r.get("route") or "") in _ALWAYS_KEEP_ROUTES
                ):
                    out.append(r)
                    if len(out) >= limit:
                        break
        return out

    def export_json(self, **query) -> str:
        return json.dumps({
            "replica": self.replica,
            "recorded": self.recorded,
            "sampled_out": self.sampled_out,
            "dropped": self.dropped,
            "max_records": self.max_records,
            "decisions": self.records(**query),
        }, default=str)

    def export_ndjson(self, **query) -> str:
        """One decision per line — the `?format=ndjson` export shape
        log shippers ingest without unwrapping."""
        return "".join(
            json.dumps(r, default=str) + "\n"
            for r in self.records(**query)
        )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "recorded": self.recorded,
                "sampled_out": self.sampled_out,
                "dropped": self.dropped,
                "denial_log_dropped": self.denial_log_dropped,
                "retained": len(self._ring),
                "pending_facts": len(self._facts),
                "routes": dict(self.route_counts),
                "tenant_keys": len(self._tenant_counts),
            }
