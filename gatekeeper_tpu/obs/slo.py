"""Live SLO & saturation plane: streaming attainment, burn-rate
alerts, and the autoscaler-facing headroom signal (ROADMAP item 3's
measurement substrate — docs/observability.md §SLO & saturation).

Before this module, SLO attainment and capacity existed only OFFLINE:
the soak reporter binned generator samples after the run and the
capacity model probed rps levels out-of-band. The serving plane itself
could not answer "am I meeting my deadline SLO right now, and how much
headroom is left?". This module is that answer, fed at the one seam
every admission already crosses — `DecisionLog.record_decision`, where
verdict, duration and `deadline_slack_ms` are in hand for all three
planes (validation / mutation / agent):

  * **Constant-memory windowed estimator** — a ring of fixed-width
    time windows per plane (and per tenant, bounded) holding
    count/ok/miss/shed plus a fixed-bucket streaming quantile sketch.
    No raw-sample retention: memory is O(planes x slots x buckets)
    regardless of traffic.
  * **Multi-window burn rate** — fast (~1 min) and slow (~15 min)
    windows judged against a configurable attainment objective
    (`SloTarget`, default the soak deadline contract). Burn rate is
    miss-fraction over error budget; `burning` latches on when the
    fast window burns past `burn_threshold` (with the slow window
    confirming) and clears only below `clear_threshold` — hysteresis,
    so a boundary-hugging burn cannot flap the signal. Entering the
    burning state fires ONE `slo_breach` flight record carrying the
    breaching window's attainment/burn numbers; the recorder embeds
    the trigger window's error decision ids (docs/observability.md
    §Flight recorder).
  * **Utilization / headroom** — an EWMA of measured device-seconds
    per admitted row (fed from the batcher's attribution seam through
    `DecisionLog.note_dispatch`) x the live arrival rate gives demand
    vs wall clock; the observed overload fraction (misses + sheds) is
    added because a plane already failing its deadline is saturated
    regardless of what the cost model claims. `saturation in [0, 1]`
    and `estimated_headroom_rps` are the `/readyz` `stats.slo`
    autoscaler contract.

Exported series: `slo_attainment{plane}`, `slo_burn_rate{plane,window}`,
`slo_error_budget_remaining`, `slo_saturation`, and the per-tenant
`slo_tenant_attainment{plane,tenant}` (cardinality-capped by the
registry like every family). `/debug/slo` serves the full snapshot on
both HTTP planes (`export_slo`).
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = [
    "QuantileSketch",
    "SloEngine",
    "SloTarget",
    "export_slo",
]

# verdicts that count as shed (resolved without evaluation) vs error
_SHED_VERDICTS = frozenset(("shed", "unavailable"))
_ERROR_VERDICTS = frozenset(("error",))


@dataclass(frozen=True)
class SloTarget:
    """The single SLO-objective definition shared by the live engine
    and the offline soak reporter (the 0.9/0.95 degrade/recover
    thresholds used to live hardcoded in soak/report.py). Scenario
    files override it via the `slo` key (`from_dict`)."""

    # attainment objective: the fraction of requests that must be
    # answered within deadline; 1 - objective is the error budget
    objective: float = 0.99
    # the deadline the live plane judges durations against; None falls
    # back to the handler's own deadline_slack (request_timeout)
    deadline_s: Optional[float] = None
    # burn-rate evaluation windows
    fast_window_s: float = 60.0
    slow_window_s: float = 900.0
    # hysteresis: burning latches ON at burn_threshold (fast window,
    # slow window confirming at slow_burn_threshold) and OFF only at
    # clear_threshold — the gap is what prevents flapping
    burn_threshold: float = 4.0
    slow_burn_threshold: float = 1.0
    clear_threshold: float = 1.0
    # minimum fast-window sample count before burn is judged (an empty
    # window must never page)
    min_samples: int = 20
    # the offline reporter's phase checks: the fault phase must drop
    # attainment below `degraded_below`, recovery must restore it to
    # `recovered_at` (previously report.py's hardcoded 0.9/0.95)
    degraded_below: float = 0.90
    recovered_at: float = 0.95

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)

    def validate(self) -> None:
        if not (0.0 < self.objective < 1.0):
            raise ValueError("objective must be in (0, 1)")
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise ValueError("burn windows must be > 0")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError("fast_window_s must be <= slow_window_s")
        if self.clear_threshold > self.burn_threshold:
            raise ValueError(
                "clear_threshold must be <= burn_threshold (hysteresis)"
            )
        if not (0.0 < self.degraded_below <= self.recovered_at <= 1.0):
            raise ValueError(
                "want 0 < degraded_below <= recovered_at <= 1"
            )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "objective": self.objective,
            "deadline_s": self.deadline_s,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "slow_burn_threshold": self.slow_burn_threshold,
            "clear_threshold": self.clear_threshold,
            "min_samples": self.min_samples,
            "degraded_below": self.degraded_below,
            "recovered_at": self.recovered_at,
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], **defaults) -> "SloTarget":
        """Build from a scenario's `slo` dict (unknown keys rejected so
        a typoed override fails the scenario load, not the analysis);
        `defaults` seed fields the dict leaves unset (the soak harness
        passes `deadline_s=scenario.deadline_s` — the deadline contract
        IS the default objective's denominator)."""
        d = dict(d or {})
        known = set(cls().to_dict())
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown SloTarget keys: {sorted(unknown)}"
            )
        merged = dict(defaults)
        merged.update(d)
        t = cls(**merged)
        t.validate()
        return t


class QuantileSketch:
    """Fixed-bucket streaming quantile estimator: geometric buckets
    from `BASE` seconds growing by `GROWTH` per bucket, value counts
    only — no raw samples, O(NBUCKETS) memory, mergeable across
    windows (why this over P2: P2 markers cannot be merged, and the
    ring needs per-window sketches summed into per-horizon quantiles).

    Error contract (tests/test_slo.py pins it on adversarial
    distributions): for values within [BASE, BASE*GROWTH^(NBUCKETS-1)]
    the estimate is the geometric midpoint of the true value's bucket,
    so the relative error is bounded by sqrt(GROWTH) - 1 (~12%).
    Values below BASE report BASE (absolute error <= 100 us); values
    above the top edge clamp into the last bucket."""

    BASE = 1e-4          # 100 us
    GROWTH = 1.25
    NBUCKETS = 64        # top edge ~128 s

    __slots__ = ("counts", "n")

    _LOG_GROWTH = math.log(GROWTH)

    def __init__(self) -> None:
        self.counts = [0] * self.NBUCKETS
        self.n = 0

    def _index(self, v: float) -> int:
        if v <= self.BASE:
            return 0
        idx = 1 + int(math.log(v / self.BASE) / self._LOG_GROWTH)
        return min(idx, self.NBUCKETS - 1)

    def add(self, v: float) -> None:
        self.counts[self._index(float(v))] += 1
        self.n += 1

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        return self

    def _edge(self, i: int) -> float:
        return self.BASE * (self.GROWTH ** i)

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate (rank int(q*(n-1)), matching
        sorted_vals[int(q*(n-1))]); None when empty."""
        if self.n <= 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        rank = int(q * (self.n - 1)) + 1  # 1-based
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                if i == 0:
                    return self.BASE
                # geometric midpoint of (edge[i-1], edge[i]]
                return math.sqrt(self._edge(i - 1) * self._edge(i))
        return self._edge(self.NBUCKETS - 1)

    def reset(self) -> None:
        for i in range(self.NBUCKETS):
            self.counts[i] = 0
        self.n = 0


class _Win:
    """One fixed-width time window's aggregates."""

    __slots__ = ("epoch", "count", "ok", "miss", "shed", "sketch")

    def __init__(self) -> None:
        self.epoch = -1
        self.count = 0
        self.ok = 0
        self.miss = 0
        self.shed = 0
        self.sketch = QuantileSketch()

    def reset(self, epoch: int) -> None:
        self.epoch = epoch
        self.count = self.ok = self.miss = self.shed = 0
        self.sketch.reset()


class _Ring:
    """`slots` fixed-width windows covering `horizon_s` (one spare so
    the current partial window never overwrites the oldest one still
    inside the horizon). Stale slots are detected by epoch tag, so a
    quiet plane costs nothing and reads correctly after any gap."""

    __slots__ = ("width", "slots", "n")

    def __init__(self, horizon_s: float, slots: int = 12) -> None:
        self.n = max(1, int(slots))
        self.width = float(horizon_s) / self.n
        self.slots = [_Win() for _ in range(self.n + 1)]

    def _win(self, now: float) -> _Win:
        epoch = int(now / self.width)
        w = self.slots[epoch % len(self.slots)]
        if w.epoch != epoch:
            w.reset(epoch)
        return w

    def add(
        self, now: float, ok: bool, shed: bool,
        duration_s: Optional[float],
    ) -> None:
        w = self._win(now)
        w.count += 1
        if shed:
            w.shed += 1
        elif ok:
            w.ok += 1
        else:
            w.miss += 1
        if duration_s is not None:
            w.sketch.add(duration_s)

    def _live(self, now: float) -> List[_Win]:
        floor = int(now / self.width) - self.n + 1
        return [w for w in self.slots if w.epoch >= floor]

    def totals(self, now: float) -> Dict[str, int]:
        live = self._live(now)
        return {
            "count": sum(w.count for w in live),
            "ok": sum(w.ok for w in live),
            "miss": sum(w.miss for w in live),
            "shed": sum(w.shed for w in live),
        }

    def quantile(self, now: float, q: float) -> Optional[float]:
        merged = QuantileSketch()
        for w in self._live(now):
            merged.merge(w.sketch)
        return merged.quantile(q)


def _attainment(t: Dict[str, int]) -> Optional[float]:
    return t["ok"] / t["count"] if t["count"] else None


class _PlaneState:
    __slots__ = ("fast", "slow", "burning")

    def __init__(self, target: SloTarget) -> None:
        self.fast = _Ring(target.fast_window_s, slots=12)
        self.slow = _Ring(target.slow_window_s, slots=15)
        self.burning = False


class SloEngine:
    """The in-process streaming SLO engine. Thread-safe; every public
    entry point is O(ring slots) worst case and never raises into the
    admission path (the DecisionLog seam wraps calls defensively
    anyway). Construct once per replica, share the replica's metrics
    registry and flight recorder."""

    def __init__(
        self,
        target: Optional[SloTarget] = None,
        metrics=None,
        recorder=None,
        replica: Optional[str] = None,
        # per-(plane, tenant) ring bound: past it new tenants aggregate
        # into the overflow counter (the metrics registry's cardinality
        # cap independently bounds the exported per-tenant series)
        max_tenants: int = 64,
        # EWMA smoothing for device-seconds-per-row
        ewma_alpha: float = 0.2,
        clock=time.monotonic,
    ):
        self.target = target or SloTarget()
        self.target.validate()
        self.metrics = metrics
        self.recorder = recorder
        self.replica = replica
        self.max_tenants = max(1, int(max_tenants))
        self.ewma_alpha = float(ewma_alpha)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._planes: Dict[str, _PlaneState] = {}
        self._tenants: Dict[str, _Ring] = {}
        self.tenant_overflow = 0
        self._cost_ewma: Optional[float] = None
        self._cost_samples = 0
        self.breaches = 0
        self.observed = 0
        self._gauge_epoch = -1

    # -- feeds ---------------------------------------------------------------

    def observe(
        self,
        plane: str,
        ok: bool,
        duration_s: Optional[float] = None,
        shed: bool = False,
        tenant: Optional[Any] = None,
    ) -> None:
        """One admission outcome. `ok` = answered within deadline
        (deny IS ok — the SLO is about answering, not admitting);
        `shed` = resolved without evaluation (queue full / deadline
        expired / fail-policy envelope), counted against attainment in
        its own bucket. Called by DecisionLog.record_decision for
        every decision BEFORE sampling, so the estimator sees the full
        stream the ring only samples."""
        now = self._clock()
        fire_ctx: Optional[Dict[str, Any]] = None
        with self._lock:
            self.observed += 1
            st = self._planes.get(plane)
            if st is None:
                st = self._planes[plane] = _PlaneState(self.target)
            st.fast.add(now, ok, shed, duration_s)
            st.slow.add(now, ok, shed, duration_s)
            tkey = self._tenant_key(plane, tenant)
            if tkey is not None:
                ring = self._tenants.get(tkey)
                if ring is None:
                    if len(self._tenants) >= self.max_tenants:
                        self.tenant_overflow += 1
                        ring = None
                    else:
                        ring = self._tenants[tkey] = _Ring(
                            self.target.fast_window_s, slots=12
                        )
                if ring is not None:
                    ring.add(now, ok, shed, duration_s)
            fire_ctx = self._evaluate_burn(plane, st, now)
            gauge_rows = self._maybe_gauge_rows(now)
        # metrics + recorder are self-locking; fire outside our lock
        if gauge_rows:
            self._export_gauges(gauge_rows)
        if fire_ctx is not None and self.recorder is not None:
            self.recorder.trigger("slo_breach", **fire_ctx)

    def reset_windows(self) -> None:
        """Drop every accumulated window (planes + tenants) and restart
        the arrival clock, keeping the cost EWMA and breach counters —
        the soak harness calls this after warmup so live attainment
        measures the same traffic the offline reporter bins."""
        with self._lock:
            self._planes.clear()
            self._tenants.clear()
            self.observed = 0
            self._t0 = self._clock()

    def note_cost(self, device_seconds: float, rows: int = 1) -> None:
        """Measured device-seconds for `rows` admitted rows (the
        batcher's attribution seam: each dispatch's device window split
        over its batch). Feeds the EWMA behind the saturation and
        headroom estimates."""
        if rows <= 0 or device_seconds < 0:
            return
        per_row = float(device_seconds) / rows
        with self._lock:
            if self._cost_ewma is None:
                self._cost_ewma = per_row
            else:
                a = self.ewma_alpha
                self._cost_ewma = a * per_row + (1 - a) * self._cost_ewma
            self._cost_samples += 1

    def cost_per_row(self) -> Optional[float]:
        """The live device-seconds-per-row EWMA (None until the first
        `note_cost`). The admission scheduler's `BatchCostModel` reads
        this to predict a candidate batch's device seconds before the
        cut (gatekeeper_tpu/sched/)."""
        with self._lock:
            return self._cost_ewma

    # -- burn-rate evaluation ------------------------------------------------

    def _burn(self, totals: Dict[str, int]) -> float:
        if not totals["count"]:
            return 0.0
        frac = (totals["miss"] + totals["shed"]) / totals["count"]
        return frac / self.target.error_budget

    def _evaluate_burn(
        self, plane: str, st: _PlaneState, now: float
    ) -> Optional[Dict[str, Any]]:
        """Hysteresis state machine; returns the slo_breach trigger
        context exactly once per entry into the burning state."""
        t = self.target
        ft = st.fast.totals(now)
        burn_fast = self._burn(ft)
        if st.burning:
            if burn_fast <= t.clear_threshold:
                st.burning = False
            return None
        if ft["count"] < t.min_samples:
            return None
        if burn_fast < t.burn_threshold:
            return None
        slo_t = st.slow.totals(now)
        if self._burn(slo_t) < t.slow_burn_threshold:
            return None
        st.burning = True
        self.breaches += 1
        return {
            "plane": plane,
            "objective": t.objective,
            "window_s": t.fast_window_s,
            "attainment_fast": _attainment(ft),
            "burn_rate_fast": round(burn_fast, 3),
            "burn_rate_slow": round(self._burn(slo_t), 3),
            "requests_fast": ft["count"],
            "misses_fast": ft["miss"],
            "sheds_fast": ft["shed"],
        }

    # -- saturation / headroom -----------------------------------------------

    def _overall_fast(self, now: float) -> Dict[str, int]:
        out = {"count": 0, "ok": 0, "miss": 0, "shed": 0}
        for st in self._planes.values():
            t = st.fast.totals(now)
            for k in out:
                out[k] += t[k]
        return out

    def _overall_slow(self, now: float) -> Dict[str, int]:
        out = {"count": 0, "ok": 0, "miss": 0, "shed": 0}
        for st in self._planes.values():
            t = st.slow.totals(now)
            for k in out:
                out[k] += t[k]
        return out

    def _utilization(self, now: float) -> Dict[str, Any]:
        t = self.target
        fast = self._overall_fast(now)
        span = min(t.fast_window_s, max(now - self._t0, 1e-6))
        arrival_rps = fast["count"] / span
        demand = (
            (self._cost_ewma or 0.0) * arrival_rps
        )
        overload = (
            (fast["miss"] + fast["shed"]) / fast["count"]
            if fast["count"] else 0.0
        )
        saturation = min(1.0, max(0.0, demand + overload))
        headroom: Optional[float] = None
        capacity: Optional[float] = None
        if self._cost_ewma and self._cost_ewma > 0:
            capacity = 1.0 / self._cost_ewma
            headroom = max(0.0, (1.0 - saturation) * capacity)
        return {
            "saturation": round(saturation, 4),
            "demand_fraction": round(min(demand, 1e9), 4),
            "overload_fraction": round(overload, 4),
            "arrival_rps": round(arrival_rps, 2),
            "device_seconds_per_row_ewma": (
                round(self._cost_ewma, 9)
                if self._cost_ewma is not None else None
            ),
            "cost_samples": self._cost_samples,
            "estimated_capacity_rps": (
                round(capacity, 1) if capacity is not None else None
            ),
            "estimated_headroom_rps": (
                round(headroom, 1) if headroom is not None else None
            ),
        }

    # -- gauge export ---------------------------------------------------------

    def _maybe_gauge_rows(self, now: float) -> Optional[List[tuple]]:
        """Gauge rows when the fast window rolled since the last
        export (caller holds the lock; emission happens outside it)."""
        if self.metrics is None:
            return None
        width = self.target.fast_window_s / 12.0
        epoch = int(now / width)
        if epoch == self._gauge_epoch:
            return None
        self._gauge_epoch = epoch
        return self._gauge_rows_locked(now)

    def _gauge_rows_locked(self, now: float) -> List[tuple]:
        rows: List[tuple] = []
        for plane, st in sorted(self._planes.items()):
            ft = st.fast.totals(now)
            att = _attainment(ft)
            if att is not None:
                rows.append(("slo_attainment", att, {"plane": plane}))
            rows.append((
                "slo_burn_rate", self._burn(ft),
                {"plane": plane, "window": "fast"},
            ))
            rows.append((
                "slo_burn_rate", self._burn(st.slow.totals(now)),
                {"plane": plane, "window": "slow"},
            ))
        slow = self._overall_slow(now)
        remaining = max(0.0, 1.0 - self._burn(slow))
        rows.append(("slo_error_budget_remaining", remaining, {}))
        util = self._utilization(now)
        rows.append(("slo_saturation", util["saturation"], {}))
        for tkey, ring in self._tenants.items():
            att = _attainment(ring.totals(now))
            if att is None:
                continue
            plane, _, tenant = tkey.partition("/")
            rows.append((
                "slo_tenant_attainment", att,
                {"plane": plane, "tenant": tenant},
            ))
        return rows

    def _export_gauges(self, rows: List[tuple]) -> None:
        # one literal call site per family: the metrics-contract scan
        # (tests/test_metrics_contract.py) matches literal names only,
        # and dynamically-named metrics are deliberately absent from
        # this codebase
        for name, value, tags in rows:
            try:
                if name == "slo_attainment":
                    self.metrics.gauge("slo_attainment", value, **tags)
                elif name == "slo_burn_rate":
                    self.metrics.gauge("slo_burn_rate", value, **tags)
                elif name == "slo_error_budget_remaining":
                    self.metrics.gauge(
                        "slo_error_budget_remaining", value, **tags
                    )
                elif name == "slo_saturation":
                    self.metrics.gauge("slo_saturation", value, **tags)
                elif name == "slo_tenant_attainment":
                    self.metrics.gauge(
                        "slo_tenant_attainment", value, **tags
                    )
            except Exception:
                pass

    # -- reads ----------------------------------------------------------------

    @staticmethod
    def _tenant_key(plane: str, tenant: Any) -> Optional[str]:
        if isinstance(tenant, dict):
            tenant = (
                tenant.get("namespace")
                or tenant.get("agent")
                or tenant.get("username")
                or ""
            )
        tenant = str(tenant or "")
        if not tenant:
            return None
        return f"{plane}/{tenant}"

    def overall_attainment(self, window: str = "slow") -> Optional[float]:
        """Attainment across planes over one burn window — the number
        the soak smoke compares against the offline report."""
        now = self._clock()
        with self._lock:
            t = (
                self._overall_fast(now) if window == "fast"
                else self._overall_slow(now)
            )
            return _attainment(t)

    def autoscaler(self) -> Dict[str, Any]:
        """The `/readyz` `stats.slo` block: the `saturation` and
        `burning` fields are the autoscaler contract (scale up when
        saturation approaches 1 or burning holds true; scale down on
        sustained headroom)."""
        now = self._clock()
        with self._lock:
            util = self._utilization(now)
            fast = self._overall_fast(now)
            return {
                "saturation": util["saturation"],
                "burning": any(
                    st.burning for st in self._planes.values()
                ),
                "estimated_headroom_rps": util["estimated_headroom_rps"],
                "arrival_rps": util["arrival_rps"],
                "attainment": _attainment(fast),
                "objective": self.target.objective,
                "breaches": self.breaches,
            }

    def snapshot(self) -> Dict[str, Any]:
        """The full `/debug/slo` body: per-plane attainment/burn/
        latency-quantiles + burning state, per-tenant fast-window
        attainment, utilization block, and the target definition."""
        now = self._clock()
        with self._lock:
            planes: Dict[str, Any] = {}
            for plane, st in sorted(self._planes.items()):
                ft = st.fast.totals(now)
                sl = st.slow.totals(now)
                p50 = st.fast.quantile(now, 0.50)
                p99 = st.fast.quantile(now, 0.99)
                planes[plane] = {
                    "attainment_fast": _attainment(ft),
                    "attainment_slow": _attainment(sl),
                    "burn_rate_fast": round(self._burn(ft), 3),
                    "burn_rate_slow": round(self._burn(sl), 3),
                    "requests_fast": ft["count"],
                    "requests_slow": sl["count"],
                    "misses_fast": ft["miss"],
                    "sheds_fast": ft["shed"],
                    "p50_ms": (
                        round(p50 * 1e3, 3) if p50 is not None else None
                    ),
                    "p99_ms": (
                        round(p99 * 1e3, 3) if p99 is not None else None
                    ),
                    "burning": st.burning,
                }
            tenants: Dict[str, Any] = {}
            for tkey, ring in sorted(self._tenants.items()):
                t = ring.totals(now)
                if not t["count"]:
                    continue
                tenants[tkey] = {
                    "attainment_fast": _attainment(t),
                    "requests_fast": t["count"],
                }
            slow = self._overall_slow(now)
            snap = {
                "replica": self.replica,
                "target": self.target.to_dict(),
                "observed": self.observed,
                "planes": planes,
                "tenants": tenants,
                "tenant_overflow": self.tenant_overflow,
                "burning": any(
                    st.burning for st in self._planes.values()
                ),
                "breaches": self.breaches,
                "error_budget_remaining": round(
                    max(0.0, 1.0 - self._burn(slow)), 4
                ),
                "utilization": self._utilization(now),
            }
        return snap


def export_slo(slo: SloEngine, path: str = "/debug/slo") -> str:
    """The one `/debug/slo` renderer both HTTP planes (health +
    metrics) share: ?plane= narrows the plane table, ?tenants=0 drops
    the tenant table (docs/observability.md §SLO & saturation)."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    snap = slo.snapshot()
    plane = (q.get("plane") or [None])[0]
    if plane:
        snap["planes"] = {
            k: v for k, v in snap["planes"].items() if k == plane
        }
        snap["tenants"] = {
            k: v for k, v in snap["tenants"].items()
            if k.startswith(f"{plane}/")
        }
    if (q.get("tenants") or ["1"])[0] in ("0", "false", "no"):
        snap.pop("tenants", None)
    return json.dumps(snap, default=str)
