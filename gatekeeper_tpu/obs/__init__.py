"""Observability: in-process tracing (spans, ring retention, JSON +
OTLP export, W3C traceparent propagation), per-constraint device-time
cost attribution, and the trip-triggered flight recorder. See
docs/observability.md for the span taxonomy and wiring map."""

from .attribution import MONO_PARTITION, CostAttributor
from .flightrecorder import FlightRecorder
from .tracer import (
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    derive_trace_id,
    format_traceparent,
    parse_traceparent,
    span_breakdown,
    start_span,
)

__all__ = [
    "NOOP_SPAN",
    "MONO_PARTITION",
    "CostAttributor",
    "FlightRecorder",
    "Span",
    "SpanContext",
    "Tracer",
    "derive_trace_id",
    "format_traceparent",
    "parse_traceparent",
    "span_breakdown",
    "start_span",
]
