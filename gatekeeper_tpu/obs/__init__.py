"""Observability: in-process tracing (spans, ring retention, JSON +
OTLP export, W3C traceparent propagation), per-constraint device-time
cost attribution, the trip-triggered flight recorder, and the
per-admission decision log. See docs/observability.md for the span
taxonomy and wiring map."""

from .attribution import MONO_PARTITION, CostAttributor
from .decisionlog import (
    DECISION_SCHEMA_FIELDS,
    DecisionLog,
    check_decision_schema,
)
from .flightrecorder import FlightRecorder
from .slo import QuantileSketch, SloEngine, SloTarget, export_slo
from .tracer import (
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    derive_trace_id,
    format_traceparent,
    parse_traceparent,
    span_breakdown,
    start_span,
)

__all__ = [
    "NOOP_SPAN",
    "DECISION_SCHEMA_FIELDS",
    "MONO_PARTITION",
    "CostAttributor",
    "DecisionLog",
    "FlightRecorder",
    "QuantileSketch",
    "SloEngine",
    "SloTarget",
    "check_decision_schema",
    "export_slo",
    "Span",
    "SpanContext",
    "Tracer",
    "derive_trace_id",
    "format_traceparent",
    "parse_traceparent",
    "span_breakdown",
    "start_span",
]
