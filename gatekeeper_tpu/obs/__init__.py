"""Observability: in-process tracing (spans, ring retention, JSON
export) threaded through the admission and audit paths. See
docs/observability.md for the span taxonomy and wiring map."""

from .tracer import (
    NOOP_SPAN,
    Span,
    SpanContext,
    Tracer,
    span_breakdown,
    start_span,
)

__all__ = [
    "NOOP_SPAN",
    "Span",
    "SpanContext",
    "Tracer",
    "span_breakdown",
    "start_span",
]
