"""Trip-triggered postmortem capture: the flight recorder.

A sick-chip event today is diagnosable only live — by the time an
operator looks, the trace ring has churned past the interesting window
and the breaker has probed itself half-closed. The flight recorder
makes the event diagnosable after the fact: when something trips, it
snapshots the evidence INTO one timestamped record —

  * the trace-ring tail (the degraded-request traces around the trip),
  * every registered state source (breaker/queue/partition snapshots),
  * the top-K per-constraint cost table (`obs.attribution`),
  * the active fault points (`faults.FAULTS.snapshot()`).

Triggers: circuit-breaker transition to OPEN (`faults/breaker.py`
fires the hook), device quarantine (`parallel/partition.py`), an
SLO-window breach in soak, and shed bursts (`MicroBatcher._shed` →
`note_shed`). Trigger call sites run under hot-path locks (the breaker
calls its hook inside ITS lock), so `trigger()` only appends to a
deque and wakes the worker — the capture itself runs on the recorder's
own thread after a short debounce window that coalesces a burst of
related triggers (breaker open + quarantine + shed storm = ONE event,
one record).

Retention is bounded twice: an in-memory ring of `max_records` (=16,
served at `/debug/flightrecords`) and, when a directory is configured
(`dir=` or `GATEKEEPER_TPU_FLIGHT_DIR`), the same bound on on-disk
JSON files. Captures are single-flight and rate-limited
(`min_interval_s`): a flapping breaker produces one record per window
plus a suppressed-trigger count, never a disk-filling stream.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["FlightRecorder"]

DEFAULT_MAX_RECORDS = 16


def _jsonable(value: Any) -> Any:
    """Best-effort JSON coercion: a state source returning exotic
    objects must degrade to its repr, never kill the capture."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


class FlightRecorder:
    def __init__(
        self,
        tracer=None,
        attributor=None,
        metrics=None,
        # obs.DecisionLog: a capture embeds the trigger window's
        # failed/degraded decision ids + trace ids, so a postmortem
        # names the exact requests behind the trip and each is
        # retrievable at /debug/decisions?trace_id= (the decision ↔
        # flight cross-link, docs/observability.md §Decision log)
        decisions=None,
        replica: Optional[str] = None,
        max_records: int = DEFAULT_MAX_RECORDS,
        dir: Optional[str] = None,
        # captures are rate-limited: triggers landing within
        # min_interval_s of the last capture are counted, not recorded
        min_interval_s: float = 5.0,
        # the coalescing window between the first trigger and the
        # snapshot — long enough for the tripping dispatch to finish
        # recording its degraded-request spans into the trace ring
        debounce_s: float = 0.25,
        trace_tail: int = 12,
        top_k_costs: int = 10,
        # shed-burst detection (note_shed): this many sheds inside the
        # window trips one "shed_burst" record
        shed_burst_threshold: int = 50,
        shed_burst_window_s: float = 5.0,
        # compile-storm detection (note_restage_failure): this many
        # restage failures inside the window — or a recompile backlog
        # at least this deep — trips one "compile_storm" record
        compile_storm_threshold: int = 8,
        compile_storm_window_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.tracer = tracer
        self.attributor = attributor
        self.metrics = metrics
        self.decisions = decisions
        self.replica = replica
        self.max_records = max(1, int(max_records))
        self.dir = dir if dir is not None else os.environ.get(
            "GATEKEEPER_TPU_FLIGHT_DIR"
        ) or None
        self.min_interval_s = min_interval_s
        self.debounce_s = debounce_s
        self.trace_tail = trace_tail
        self.top_k_costs = top_k_costs
        self.shed_burst_threshold = max(1, int(shed_burst_threshold))
        self.shed_burst_window_s = shed_burst_window_s
        self.compile_storm_threshold = max(1, int(compile_storm_threshold))
        self.compile_storm_window_s = compile_storm_window_s
        self._clock = clock
        self._sources: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._records: List[Dict[str, Any]] = []
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._last_capture: Optional[float] = None
        self._sheds: deque = deque()  # monotonic stamps per plane-shed
        self._shed_lock = threading.Lock()
        self._restage_fails: deque = deque()  # stamps per restage fail
        self.captured = 0
        self.suppressed = 0

    # -- wiring ---------------------------------------------------------------

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a state snapshot callable captured into every
        record under `state[name]` (breaker banks, partition plans,
        queue depths). Evaluated on the recorder thread — a raising
        source records its error string, never aborts the capture."""
        self._sources[name] = fn

    # -- triggers -------------------------------------------------------------

    def trigger(self, reason: str, **context) -> None:
        """Request a postmortem capture. Non-blocking and safe under
        ANY caller lock (the breaker fires this inside its own lock):
        the event is queued and the worker thread does the capture
        after the debounce window."""
        if self._stop.is_set():
            return
        with self._lock:
            self._pending.append({
                "reason": reason,
                "t_monotonic": self._clock(),
                "ts": time.time(),
                "context": {k: _jsonable(v) for k, v in context.items()},
            })
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="gk-flightrecorder",
                    daemon=True,
                )
                self._thread.start()
        self._wake.set()

    def note_shed(self, plane: str = "validation") -> None:
        """Shed-burst detector: each shed stamps the rolling window;
        crossing the threshold triggers ONE `shed_burst` capture (the
        rate limit absorbs the rest of the storm)."""
        now = self._clock()
        fire = False
        with self._shed_lock:
            self._sheds.append(now)
            horizon = now - self.shed_burst_window_s
            while self._sheds and self._sheds[0] < horizon:
                self._sheds.popleft()
            if len(self._sheds) >= self.shed_burst_threshold:
                self._sheds.clear()
                fire = True
        if fire:
            self.trigger(
                "shed_burst", plane=plane,
                threshold=self.shed_burst_threshold,
                window_s=self.shed_burst_window_s,
            )

    def note_restage_failure(
        self, plane: str = "validation", backlog: int = 0
    ) -> None:
        """Compile-storm detector (docs/compile.md §Failure modes): a
        burst of restage failures inside the rolling window — or a
        recompile backlog already at the threshold — trips ONE
        `compile_storm` capture; the `programs` source then embeds the
        program-store state table in the record. Debounce + rate limit
        are the shared trigger machinery."""
        now = self._clock()
        fire = int(backlog) >= self.compile_storm_threshold
        with self._shed_lock:
            self._restage_fails.append(now)
            horizon = now - self.compile_storm_window_s
            while self._restage_fails and self._restage_fails[0] < horizon:
                self._restage_fails.popleft()
            if len(self._restage_fails) >= self.compile_storm_threshold:
                self._restage_fails.clear()
                fire = True
        if fire:
            self.trigger(
                "compile_storm", plane=plane, backlog=int(backlog),
                threshold=self.compile_storm_threshold,
                window_s=self.compile_storm_window_s,
            )

    # -- the worker -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait()
            self._wake.clear()
            if self._stop.is_set():
                return
            with self._lock:
                if not self._pending:
                    continue
            # debounce: let the tripping dispatch finish stamping its
            # spans, and let sibling triggers (quarantine riding a
            # breaker open) coalesce into the same record
            if self.debounce_s > 0:
                self._stop.wait(self.debounce_s)
            with self._lock:
                triggers = list(self._pending)
                self._pending.clear()
            if not triggers:
                continue
            now = self._clock()
            if (
                self._last_capture is not None
                and now - self._last_capture < self.min_interval_s
            ):
                self.suppressed += len(triggers)
                if self.metrics is not None:
                    self.metrics.record(
                        "flight_records_suppressed_total", len(triggers),
                        trigger=triggers[0]["reason"],
                    )
                continue
            self._last_capture = now
            self._capture(triggers)

    def _capture(self, triggers: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        record: Dict[str, Any] = {
            "id": f"fr-{seq:05d}",
            "ts": time.time(),
            "replica": self.replica,
            "trigger": triggers[0]["reason"],
            "triggers": triggers,
        }
        if self.tracer is not None:
            try:
                record["trace_tail"] = self.tracer.recent(self.trace_tail)
            except Exception as e:
                record["trace_tail_error"] = str(e)
        if self.attributor is not None:
            try:
                record["costs"] = self.attributor.table(self.top_k_costs)
            except Exception as e:
                record["costs_error"] = str(e)
        if self.decisions is not None:
            # the trigger window's failed/degraded decisions: ids +
            # trace ids only (the full records stay in the decision
            # ring — one source of truth, joined by id/trace_id)
            try:
                window = self.decisions.recent_errors(
                    window_s=max(self.min_interval_s * 6, 30.0)
                )
                record["decisions"] = [
                    {
                        "id": d.get("id"),
                        "trace_id": d.get("trace_id"),
                        "plane": d.get("plane"),
                        "verdict": d.get("verdict"),
                        "route": d.get("route"),
                    }
                    for d in window
                ]
            except Exception as e:
                record["decisions_error"] = str(e)
        try:
            from ..faults import FAULTS

            record["faults"] = _jsonable(FAULTS.snapshot())
        except Exception as e:
            record["faults_error"] = str(e)
        state: Dict[str, Any] = {}
        for name, fn in list(self._sources.items()):
            try:
                state[name] = _jsonable(fn())
            except Exception as e:
                state[name] = {"error": str(e)}
        record["state"] = state
        with self._lock:
            self._records.append(record)
            if len(self._records) > self.max_records:
                del self._records[: len(self._records) - self.max_records]
        self.captured += 1
        if self.metrics is not None:
            self.metrics.record(
                "flight_records_total", 1, trigger=record["trigger"],
            )
        self._persist(record)

    def _persist(self, record: Dict[str, Any]) -> None:
        """Bounded on-disk mirror: one JSON file per record, oldest
        pruned past `max_records`. Best-effort — a full disk must not
        take the recorder (or its trigger sites) down."""
        if not self.dir:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(
                self.dir, f"flightrecord-{record['id']}.json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, path)
            files = sorted(
                f for f in os.listdir(self.dir)
                if f.startswith("flightrecord-") and f.endswith(".json")
            )
            for stale in files[: max(0, len(files) - self.max_records)]:
                try:
                    os.remove(os.path.join(self.dir, stale))
                except OSError:
                    pass
        except OSError:
            pass

    # -- read ----------------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        """Newest-first record list (the `/debug/flightrecords`
        payload body)."""
        with self._lock:
            return list(reversed(self._records))

    def export_json(self) -> str:
        return json.dumps({
            "replica": self.replica,
            "captured": self.captured,
            "suppressed": self.suppressed,
            "max_records": self.max_records,
            "records": self.records(),
        })

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._records)
        return {
            "captured": self.captured,
            "suppressed": self.suppressed,
            "retained": n,
        }

    # -- lifecycle ------------------------------------------------------------

    def flush(self, timeout: float = 2.0) -> bool:
        """Wait until the pending trigger queue has drained (tests and
        harness teardown); True when it drained in time."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return True
            time.sleep(0.01)
        return False

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
