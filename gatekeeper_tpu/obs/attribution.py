"""Per-constraint device-time cost attribution.

ROADMAP item 1 says the fused path collapses as constraints grow, but
until now nothing in the system could say WHICH constraints cost what —
`driver_phase_seconds` stops at whole-batch granularity. This module is
the instrument the pruning/partitioning work aims with:

  * the driver measures per-dispatch device-execute time at the
    `query_many_subset` / `_eval_reviews_split` seam (the PR 9
    partition boundary makes per-subset timing exact, not guessed);
  * a static cost model apportions that measured time across the
    constraints the dispatch evaluated: each constraint's weight is
    analyzer/compiler-derived — program expression rows × row-feature
    width (`TpuDriver._static_cost`), so a heavyweight inventory-join
    template is charged more of the window than a one-clause label
    check sharing its partition;
  * the attributor accumulates `{(kind, name, partition) -> seconds}`
    and emits `constraint_device_seconds_total{kind,name,partition}`
    (the metrics-registry cardinality guard bounds pathological
    constraint churn), plus the sorted top-K table `/debug/costs`
    serves with share-of-plane fractions.

The invariant the bench pins (`bench_webhook.py --attribution`):
attributed seconds sum to the measured device-execute total — the
model changes WHO is charged, never HOW MUCH.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["CostAttributor"]

# the monolithic (non-partitioned) dispatch's partition label
MONO_PARTITION = "mono"


class CostAttributor:
    """Accumulates apportioned device-execute seconds per constraint.

    Thread-safe; `note_dispatch` is called on the driver's dispatch
    path under its serving mutex, so the work here is one weighted
    split plus dict adds — no I/O, no metric emission beyond the
    registry's own lock."""

    def __init__(self, metrics=None, replica: Optional[str] = None):
        self.metrics = metrics
        self.replica = replica
        self._lock = threading.Lock()
        # (kind, name, partition) -> attributed seconds
        self._costs: Dict[Tuple[str, str, str], float] = {}
        self.total_seconds = 0.0
        self.dispatches = 0

    def reset(self) -> None:
        """Zero the accumulation (bench rungs measure deltas; the
        Prometheus counters stay monotonic — only the table resets)."""
        with self._lock:
            self._costs = {}
            self.total_seconds = 0.0
            self.dispatches = 0

    def note_dispatch(
        self,
        entries: Sequence[Tuple[str, str, float]],
        device_seconds: float,
        partition: Optional[Any] = None,
    ) -> None:
        """Apportion one dispatch's measured device-execute window over
        `entries` = [(kind, name, static_weight)]. Zero-weight sets
        split evenly — a window someone paid must be charged to
        someone, or the sums check drifts."""
        if not entries or device_seconds <= 0.0:
            return
        part = MONO_PARTITION if partition is None else str(partition)
        total_w = sum(max(0.0, w) for _, _, w in entries)
        n = len(entries)
        with self._lock:
            self.dispatches += 1
            self.total_seconds += device_seconds
            for kind, name, w in entries:
                share = (
                    (max(0.0, w) / total_w)
                    if total_w > 0
                    else (1.0 / n)
                )
                dt = device_seconds * share
                key = (kind, name, part)
                self._costs[key] = self._costs.get(key, 0.0) + dt
        if self.metrics is not None:
            # replica identity rides the series when set (constant per
            # registry — fleet replicas own one registry each, so this
            # adds identification, not cardinality)
            extra = (
                {"replica": self.replica}
                if self.replica is not None
                else {}
            )
            for kind, name, w in entries:
                share = (
                    (max(0.0, w) / total_w) if total_w > 0 else (1.0 / n)
                )
                self.metrics.record(
                    "constraint_device_seconds_total",
                    device_seconds * share,
                    kind=kind, name=name, partition=part, **extra,
                )

    # -- read ----------------------------------------------------------------

    def table(self, k: Optional[int] = 10) -> Dict[str, Any]:
        """The `/debug/costs` document: constraints aggregated across
        partitions, sorted costliest-first, with share-of-plane
        fractions; `k=None` returns every row."""
        with self._lock:
            total = self.total_seconds
            by_constraint: Dict[Tuple[str, str], Dict[str, Any]] = {}
            for (kind, name, part), secs in self._costs.items():
                row = by_constraint.setdefault(
                    (kind, name),
                    {"kind": kind, "name": name, "seconds": 0.0,
                     "partitions": {}},
                )
                row["seconds"] += secs
                row["partitions"][part] = (
                    row["partitions"].get(part, 0.0) + secs
                )
            dispatches = self.dispatches
        rows = sorted(
            by_constraint.values(),
            key=lambda r: (-r["seconds"], r["kind"], r["name"]),
        )
        if k is not None:
            dropped = max(0, len(rows) - k)
            rows = rows[:k]
        else:
            dropped = 0
        out_rows: List[Dict[str, Any]] = []
        for r in rows:
            out_rows.append({
                "kind": r["kind"],
                "name": r["name"],
                "seconds": round(r["seconds"], 6),
                "share": round(r["seconds"] / total, 4) if total else 0.0,
                "partitions": {
                    p: round(s, 6)
                    for p, s in sorted(r["partitions"].items())
                },
            })
        doc: Dict[str, Any] = {
            "total_device_seconds": round(total, 6),
            "dispatches": dispatches,
            "constraints": len(by_constraint),
            "rows_omitted": dropped,
            "rows": out_rows,
        }
        if self.replica is not None:
            doc["replica"] = self.replica
        return doc

    def top(self, k: int = 10) -> List[Dict[str, Any]]:
        """Top-K costliest constraints (the bench SUMMARY's target
        list for ROADMAP item 1's pruning work)."""
        return self.table(k)["rows"]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "total_device_seconds": round(self.total_seconds, 6),
                "dispatches": self.dispatches,
                "series": len(self._costs),
            }
