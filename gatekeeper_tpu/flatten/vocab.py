"""Append-only string vocabulary with intern-time precomputation.

Ids are stable (append-only), so growing the vocab never invalidates
previously encoded tensors. Value-typed interning (`val_id`) tags
non-string JSON scalars so cross-type equality can never alias.

Expensive string predicates (regex match, prefix match, k8s quantity
parsing) are evaluated once per distinct vocab entry and memoized —
the TPU analog of doing `re_match`/`startswith`/quantity parsing inside
OPA's interpreter loop per object (e.g. the reference library's
k8srequiredlabels regex check and k8scontainerlimits quantity math).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

_QUNSET = object()

# k8s resource.Quantity suffixes (apimachinery resource.ParseQuantity)
_QUANTITY_RE = re.compile(
    r"^([+-]?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
    r"(m|k|M|G|T|P|E|Ki|Mi|Gi|Ti|Pi|Ei)?$"
)
_SUFFIX = {
    None: 1.0,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
    "Ei": 2.0**60,
}


def parse_quantity(s: str) -> Optional[float]:
    """Parse a k8s resource quantity ("100m", "1Gi", "2") to a float."""
    if not isinstance(s, str):
        return None
    m = _QUANTITY_RE.match(s.strip())
    if not m:
        return None
    return float(m.group(1)) * _SUFFIX[m.group(2)]


class Vocab:
    """Interned strings + per-entry predicate caches."""

    def __init__(self):
        self._ids: Dict[str, int] = {}
        self._strs: List[str] = []
        # entry-id -> parsed quantity (or None)
        self._quantity: List[Optional[float]] = []
        # regex pattern -> {entry_id: bool} lazy caches
        self._regex_cache: Dict[str, Dict[int, bool]] = {}
        self._prefix_cache: Dict[str, Dict[int, bool]] = {}
        self._vid_quantity: Dict[int, Optional[float]] = {}

    def __len__(self) -> int:
        return len(self._strs)

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
            self._quantity.append(parse_quantity(s))
        return i

    def lookup(self, s: str) -> int:
        """Id of s, or -1 if never interned (safe for probe-only queries)."""
        return self._ids.get(s, -1)

    def string(self, i: int) -> str:
        return self._strs[i]

    def quantity(self, i: int) -> Optional[float]:
        return self._quantity[i]

    def quantity_of_val_id(self, vid: int) -> Optional[float]:
        """Quantity parse of a typed value entry ("s:..." strings only),
        memoized per entry — avoids interning the raw string a second
        time."""
        q = self._vid_quantity.get(vid)
        if q is _QUNSET or q is None and vid not in self._vid_quantity:
            s = self.string(vid)
            q = parse_quantity(s[2:]) if s.startswith("s:") else None
            self._vid_quantity[vid] = q
        return q

    # -- typed value interning ---------------------------------------------

    def val_id(self, v: Any) -> int:
        """Intern an arbitrary JSON scalar with a type tag, so "1" != 1 and
        "true" != true under id equality. Numbers are normalized (1.0 and 1
        share an id) to match Rego numeric equality."""
        if isinstance(v, str):
            return self.intern("s:" + v)
        if (
            isinstance(v, float)
            and not isinstance(v, bool)
            and v.is_integer()
        ):
            v = int(v)
        return self.intern("j:" + json.dumps(v, sort_keys=True))

    def str_id(self, v: str) -> int:
        return self.intern("s:" + v)

    def str_lookup(self, v: str) -> int:
        return self.lookup("s:" + v)

    # -- precomputed predicates --------------------------------------------

    def regex_matches(self, pattern: str, entry_id: int) -> bool:
        cache = self._regex_cache.setdefault(pattern, {})
        hit = cache.get(entry_id)
        if hit is None:
            s = self.string(entry_id)
            if s.startswith("s:"):
                s = s[2:]
            try:
                hit = re.search(pattern, s) is not None
            except re.error:
                hit = False
            cache[entry_id] = hit
        return hit

    def prefix_matches(self, prefix: str, entry_id: int) -> bool:
        cache = self._prefix_cache.setdefault(prefix, {})
        hit = cache.get(entry_id)
        if hit is None:
            s = self.string(entry_id)
            if s.startswith("s:"):
                s = s[2:]
            hit = s.startswith(prefix)
            cache[entry_id] = hit
        return hit


class OverlayVocab(Vocab):
    """Ephemeral per-batch view over a base Vocab.

    Strings already in the base resolve to their base ids; novel strings
    intern LOCALLY with ids >= base_len and die with the overlay. This
    is what keeps the admission path sustainable: every webhook batch
    carries fresh object names, and interning them globally would grow
    the vocab (and every [V]-shaped device table) forever — per-batch
    table re-uploads and a memory leak. The driver ships the overlay's
    tiny table/pattern rows alongside the batch instead
    (StrTables.fill_overlay / PatternRegistry.classify_overlay), and the
    kernels gather two-level (base tables for ids < base_len, overlay
    blocks above).

    Implementation: CHAIN LOOKUP — the base dict resolves first (its
    entries below the base_len snapshot), misses intern into local
    structures with offset ids. Construction is O(1), not an
    O(|vocab|) copy per admission micro-batch (ADVICE r4: the copy cost
    several ms at the 100k-corpus steady state, on the latency path the
    overlay exists to protect). The base is never mutated; predicate
    lookups on base ids DELEGATE to the base (sharing its bounded
    memos), local ids memoize locally and die with the overlay. The
    native C encoder chains the same way (flatten.c intern with
    base_ids/base_len)."""

    def __init__(self, base: Vocab):
        self.base = base
        self.base_len = len(base._strs)
        self._ids: Dict[str, int] = {}  # local, values offset by base_len
        self._strs: List[str] = []  # local, position-indexed
        self._quantity: List[Optional[float]] = []
        self._regex_cache: Dict[str, Dict[int, bool]] = {}
        self._prefix_cache: Dict[str, Dict[int, bool]] = {}
        self._vid_quantity: Dict[int, Optional[float]] = {}

    def __len__(self) -> int:
        return self.base_len + len(self._strs)

    def intern(self, s: str) -> int:
        i = self.base._ids.get(s)
        if i is not None and i < self.base_len:
            return i
        j = self._ids.get(s)
        if j is None:
            j = self.base_len + len(self._strs)
            self._ids[s] = j
            self._strs.append(s)
            self._quantity.append(parse_quantity(s))
        return j

    def lookup(self, s: str) -> int:
        i = self.base._ids.get(s)
        if i is not None and i < self.base_len:
            return i
        return self._ids.get(s, -1)

    def string(self, i: int) -> str:
        if i < self.base_len:
            return self.base._strs[i]
        return self._strs[i - self.base_len]

    def quantity(self, i: int) -> Optional[float]:
        if i < self.base_len:
            return self.base._quantity[i]
        return self._quantity[i - self.base_len]

    def quantity_of_val_id(self, vid: int) -> Optional[float]:
        if vid < self.base_len:
            return self.base.quantity_of_val_id(vid)
        return super().quantity_of_val_id(vid)

    def regex_matches(self, pattern: str, entry_id: int) -> bool:
        if entry_id < self.base_len:
            return self.base.regex_matches(pattern, entry_id)
        return super().regex_matches(pattern, entry_id)

    def prefix_matches(self, prefix: str, entry_id: int) -> bool:
        if entry_id < self.base_len:
            return self.base.prefix_matches(prefix, entry_id)
        return super().prefix_matches(prefix, entry_id)

    @property
    def local_count(self) -> int:
        return len(self._strs)
