"""Object flattening: K8s unstructured JSON → fixed-shape token tensors.

The TPU-side analog of the reference target handler's data model
(pkg/target/target.go ProcessData/HandleReview): host-side encoding of
ragged JSON into dense integer/float columns that the JAX kernels consume.
All string work (interning, regex, prefix tests, k8s quantity parsing)
happens once per distinct string at intern time and is amortized across the
resource batch — the device only ever sees int32/float32 tensors.
"""

from .vocab import Vocab, parse_quantity  # noqa: F401
from .encoder import (  # noqa: F401
    TokenTable,
    ReviewFeatures,
    FeatureBatch,
    encode_review_features,
    batch_review_features,
    flatten_leaves,
    encode_token_table,
)
