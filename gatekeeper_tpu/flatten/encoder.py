"""Review/object encoders: JSON → dense numpy columns.

Two encodings:

1. **Match features** (`ReviewFeatures`/`FeatureBatch`): the per-review
   fields the constraint match kernel needs — gvk ids, effective namespace
   name, object/oldObject label pairs, resolved namespace-selector labels.
   Mirrors exactly what the reference's Rego matching library reads from
   `input.review` (pkg/target/target_template_source.go:131-386).

2. **Token table** (`TokenTable`): the generic flattened-leaf encoding
   `(schema_path, idx0, idx1, kind, value_id, value_num)` that compiled
   template kernels evaluate against. Array indices are lifted out of the
   path (two levels — enough for containers[i].ports[j]-shaped data) so a
   single schema-path id covers every element and per-element violations
   keep their index.

Padding is bucketed to powers of two so jit specializations are reused
across batches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from ..constraint import match as M
from .vocab import Vocab

# token value kinds
K_NULL, K_BOOL, K_NUM, K_STR, K_EMPTY_OBJ, K_EMPTY_ARR = 0, 1, 2, 3, 4, 5


def esc_seg(key: str) -> str:
    """Escape an object key for use as a path segment: "." would corrupt
    segment splitting (annotation keys like kubernetes.io/ingress.class)
    and a literal "#" would collide with the array marker."""
    if "%" in key or "." in key or key == "#":
        key = key.replace("%", "%25").replace(".", "%2E")
        if key == "#":
            key = "%23"
    return key


def unesc_seg(seg: str) -> str:
    if "%" not in seg:
        return seg
    return seg.replace("%23", "#").replace("%2E", ".").replace("%25", "%")


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Token table


def flatten_leaves(
    obj: Any,
) -> Iterator[Tuple[str, int, int, int, Optional[Any], float]]:
    """Yield (schema_path, idx0, idx1, kind, raw_value, num_value) leaves.

    schema_path joins object keys with "." and replaces array levels with
    "#"; idx0/idx1 carry the first two array indices (-1 when absent).
    Empty objects/arrays are emitted as their own kind so `count`/exists
    semantics survive flattening.
    """

    def rec(v: Any, path: List[str], idx: Tuple[int, int]):
        # row-emit entry point (docs/ingest.md): an ingest
        # LazyObject already carries the rows this walk would
        # produce, scanned straight off the wire — re-root them here
        # instead of re-walking (and re-materializing) the subtree.
        # Inside an array (idx set) rows would need index rewrites,
        # so that rare shape falls through to the normal dict walk.
        pre = getattr(v, "_preflat_rows", None)
        if pre is not None and idx == (-1, -1):
            if path:
                prefix = ".".join(path) + "."
                for rp, a, b, k, raw, num in pre:
                    yield prefix + rp, a, b, k, raw, num
            else:
                yield from pre
            return
        if isinstance(v, dict):
            if not v:
                yield ".".join(path), idx[0], idx[1], K_EMPTY_OBJ, None, 0.0
                return
            for k in v:
                path.append(esc_seg(str(k)))
                yield from rec(v[k], path, idx)
                path.pop()
        elif isinstance(v, list):
            if not v:
                yield ".".join(path), idx[0], idx[1], K_EMPTY_ARR, None, 0.0
                return
            path.append("#")
            for i, item in enumerate(v):
                if idx[0] < 0:
                    nidx = (i, -1)
                elif idx[1] < 0:
                    nidx = (idx[0], i)
                else:
                    nidx = idx  # >2 array levels: indices saturate
                yield from rec(item, path, nidx)
            path.pop()
        elif isinstance(v, bool):
            yield ".".join(path), idx[0], idx[1], K_BOOL, v, 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            yield ".".join(path), idx[0], idx[1], K_NUM, v, float(v)
        elif isinstance(v, str):
            yield ".".join(path), idx[0], idx[1], K_STR, v, 0.0
        elif v is None:
            yield ".".join(path), idx[0], idx[1], K_NULL, None, 0.0

    yield from rec(obj, [], (-1, -1))


@dataclass
class TokenTable:
    """Dense token columns for a batch of objects: shape [N, L]."""

    spath: np.ndarray  # int32 schema-path id (-1 pad)
    idx0: np.ndarray  # int32 first array index (-1 none)
    idx1: np.ndarray  # int32 second array index (-1 none)
    kind: np.ndarray  # int32 K_* (-1 pad)
    vid: np.ndarray  # int32 typed value id (-1 for non-scalar)
    vnum: np.ndarray  # float32 numeric view (quantities parsed)
    n_tokens: np.ndarray  # int32 [N] true token counts (pre-truncation)
    overflow: np.ndarray  # bool [N] true if object did not fit in L

    @property
    def shape(self):
        return self.spath.shape


def _carries_preflat(obj: Any) -> bool:
    """True when `obj` is — or holds at top level — an ingest
    LazyObject. The C flattener walks raw dict storage and would see
    only the lifted keys of a lazy object; such batches must take the
    Python path, where flatten_leaves re-roots the scanned rows."""
    if getattr(obj, "_preflat_rows", None) is not None:
        return True
    if type(obj) is dict:
        for v in obj.values():
            if getattr(v, "_preflat_rows", None) is not None:
                return True
    return False


def encode_token_table(
    objs: Sequence[Any], vocab: Vocab, max_len: Optional[int] = None
) -> TokenTable:
    objs = list(objs)
    native = None if any(
        _carries_preflat(o) for o in objs
    ) else _flatten_native()
    if native is not None:
        try:
            return _encode_token_table_native(
                native, list(objs), vocab, max_len
            )
        except Exception:
            pass  # any native failure degrades to the Python encoder
    rows = []
    for obj in objs:
        row = []
        for spath, i0, i1, kind, raw, num in flatten_leaves(obj):
            pid = vocab.intern("p:" + spath)
            if kind == K_STR:
                vid = vocab.str_id(raw)
                q = vocab.quantity_of_val_id(vid)
                num = q if q is not None else 0.0
            elif kind in (K_BOOL, K_NUM, K_NULL):
                vid = vocab.val_id(raw)
            else:
                vid = -1
            row.append((pid, i0, i1, kind, vid, num))
        rows.append(row)
    longest = max((len(r) for r in rows), default=1)
    L = max_len if max_len is not None else _bucket(max(longest, 1), lo=32)
    N = len(rows)
    spath = np.full((N, L), -1, np.int32)
    idx0 = np.full((N, L), -1, np.int32)
    idx1 = np.full((N, L), -1, np.int32)
    kind = np.full((N, L), -1, np.int32)
    vid = np.full((N, L), -1, np.int32)
    vnum = np.zeros((N, L), np.float32)
    n_tokens = np.zeros((N,), np.int32)
    overflow = np.zeros((N,), bool)
    for n, row in enumerate(rows):
        n_tokens[n] = len(row)
        if len(row) > L:
            overflow[n] = True
            row = row[:L]
        for l, (p, i0, i1, k, v, num) in enumerate(row):
            spath[n, l] = p
            idx0[n, l] = i0
            idx1[n, l] = i1
            kind[n, l] = k
            vid[n, l] = v
            vnum[n, l] = num
    return TokenTable(spath, idx0, idx1, kind, vid, vnum, n_tokens, overflow)


def _flatten_native():
    from ..native import load_flatten_native

    return load_flatten_native()


def _encode_token_table_native(
    native, objs: list, vocab: Vocab, max_len: Optional[int]
) -> TokenTable:
    """C flattener path: flat columns + row offsets from the extension,
    padded into [N, L] with vectorized scatter."""
    from .vocab import parse_quantity

    base = getattr(vocab, "base", None)
    if base is not None:
        # OverlayVocab chain mode: local structures + read-only base
        # dict; the C intern assigns offset ids exactly like
        # OverlayVocab.intern
        sp_b, i0_b, i1_b, k_b, v_b, num_b, off_b = native.encode_rows(
            objs, vocab._ids, vocab._strs, vocab._quantity,
            parse_quantity, base._ids, vocab.base_len,
        )
    else:
        sp_b, i0_b, i1_b, k_b, v_b, num_b, off_b = native.encode_rows(
            objs, vocab._ids, vocab._strs, vocab._quantity, parse_quantity
        )
    flat_sp = np.frombuffer(sp_b, np.int32)
    flat_i0 = np.frombuffer(i0_b, np.int32)
    flat_i1 = np.frombuffer(i1_b, np.int32)
    flat_k = np.frombuffer(k_b, np.int32)
    flat_v = np.frombuffer(v_b, np.int32)
    flat_num = np.frombuffer(num_b, np.float32)
    off = np.frombuffer(off_b, np.int32)
    N = len(objs)
    lens = off[1:] - off[:-1]
    longest = int(lens.max(initial=0))
    L = max_len if max_len is not None else _bucket(max(longest, 1), lo=32)
    overflow = lens > L
    n_tokens = lens.astype(np.int32)
    keep = np.minimum(lens, L).astype(np.int64)
    # (row, col) scatter indices for every kept token, fully vectorized:
    # cols restart at 0 per row (ramp minus per-row start), src follows
    # the flat row offsets
    rows_idx = np.repeat(np.arange(N), keep)
    starts = np.concatenate([[0], np.cumsum(keep)[:-1]]) if N else (
        np.zeros((0,), np.int64)
    )
    ramp = np.arange(int(keep.sum()), dtype=np.int64)
    cols_idx = ramp - np.repeat(starts, keep)
    src = np.repeat(off[:-1].astype(np.int64), keep) + cols_idx
    spath = np.full((N, L), -1, np.int32)
    idx0 = np.full((N, L), -1, np.int32)
    idx1 = np.full((N, L), -1, np.int32)
    kind = np.full((N, L), -1, np.int32)
    vid = np.full((N, L), -1, np.int32)
    vnum = np.zeros((N, L), np.float32)
    spath[rows_idx, cols_idx] = flat_sp[src]
    idx0[rows_idx, cols_idx] = flat_i0[src]
    idx1[rows_idx, cols_idx] = flat_i1[src]
    kind[rows_idx, cols_idx] = flat_k[src]
    vid[rows_idx, cols_idx] = flat_v[src]
    vnum[rows_idx, cols_idx] = flat_num[src]
    return TokenTable(
        spath, idx0, idx1, kind, vid, vnum, n_tokens, overflow.astype(bool)
    )


def mask_token_table(
    table: TokenTable,
    keep_id_fn: Callable[[int], bool],
    lo: int = 32,
) -> Tuple[TokenTable, int]:
    """Drop tokens whose schema-path vocab id fails `keep_id_fn`
    (statically-dead columns per the IR liveness analysis), compacting
    survivors to the front of each row and re-bucketing L. Returns the
    filtered table plus the number of token slots dropped.

    `overflow` is preserved from the input table, never recomputed: an
    overflowed row was truncated at the ORIGINAL L and may have lost
    live tokens, so it must keep routing to the interpreter regardless
    of how small it looks after filtering. `n_tokens` becomes the kept
    count (the filtered table's true occupancy).
    """
    sp = table.spath
    uniq = np.unique(sp)
    keep_ids = np.array(
        [int(p) for p in uniq if p >= 0 and keep_id_fn(int(p))],
        dtype=np.int32,
    )
    keep = np.isin(sp, keep_ids)
    skipped = int((sp >= 0).sum() - keep.sum())
    if skipped == 0:
        return table, 0
    N = sp.shape[0]
    kept = keep.sum(axis=1).astype(np.int64)
    L = _bucket(int(max(kept.max(initial=0), 1)), lo=lo)
    rows_idx, src_cols = np.nonzero(keep)  # row-major: order preserved
    starts = np.concatenate([[0], np.cumsum(kept)[:-1]]) if N else (
        np.zeros((0,), np.int64)
    )
    cols_idx = np.arange(int(kept.sum()), dtype=np.int64) - np.repeat(
        starts, kept
    )
    spath = np.full((N, L), -1, np.int32)
    idx0 = np.full((N, L), -1, np.int32)
    idx1 = np.full((N, L), -1, np.int32)
    kind = np.full((N, L), -1, np.int32)
    vid = np.full((N, L), -1, np.int32)
    vnum = np.zeros((N, L), np.float32)
    spath[rows_idx, cols_idx] = sp[rows_idx, src_cols]
    idx0[rows_idx, cols_idx] = table.idx0[rows_idx, src_cols]
    idx1[rows_idx, cols_idx] = table.idx1[rows_idx, src_cols]
    kind[rows_idx, cols_idx] = table.kind[rows_idx, src_cols]
    vid[rows_idx, cols_idx] = table.vid[rows_idx, src_cols]
    vnum[rows_idx, cols_idx] = table.vnum[rows_idx, src_cols]
    return (
        TokenTable(
            spath,
            idx0,
            idx1,
            kind,
            vid,
            vnum,
            kept.astype(np.int32),
            table.overflow.copy(),
        ),
        skipped,
    )


# ---------------------------------------------------------------------------
# Match features

_UNDEF = -1  # undefined id sentinel


@dataclass
class ReviewFeatures:
    """Per-review scalar/label features for the match kernel."""

    group_id: int
    kind_id: int
    kind_defined: bool  # review has a `kind` field at all (hoisting gate)
    is_ns: bool
    has_namespace: bool  # get_default(review, "namespace", "") != ""
    ns_name_id: int  # effective get_ns_name (or -1 undefined)
    obj_present: bool
    old_present: bool
    obj_labels: List[Tuple[int, int]]
    old_labels: List[Tuple[int, int]]
    nssel_defined: bool  # get_ns produced at least one candidate
    nssel_labels: List[Tuple[int, int]]  # primary candidate's labels
    # a second get_ns candidate with empty labels exists (the
    # `_unstable.namespace: false` partial-set case) or the primary itself
    # is empty — the kernel ORs in the selector-matches-empty-labels result
    nssel_empty: bool


def _label_pairs(labels: Any, vocab: Vocab) -> List[Tuple[int, int]]:
    if not isinstance(labels, dict):
        return []
    out = []
    for k, v in labels.items():
        out.append((vocab.str_id(str(k)), vocab.val_id(v)))
    return out


def _obj_labels(obj: Any) -> Any:
    meta = M.get_default(obj, "metadata", {})
    return M.get_default(meta, "labels", {})


def encode_review_features(
    review: Dict[str, Any], ns_cache: Dict[str, Any], vocab: Vocab
) -> ReviewFeatures:
    """Feature extraction mirroring match.py's field helpers bit-for-bit.

    `ns_cache` is data.external.<target>.cluster.v1.Namespace (audit and
    webhook reviews both resolve namespaceSelector through `get_ns`, with
    `_unstable.namespace` taking precedence)."""
    k = review.get("kind") if isinstance(review, dict) else None
    kind_defined = isinstance(review, dict) and "kind" in review
    k = k if isinstance(k, dict) else {}
    group = k.get("group")
    kind = k.get("kind")
    is_ns = kind_defined and group == "" and kind == "Namespace"

    ns_val = M.get_default(review, "namespace", "")
    has_namespace = ns_val != ""

    ns_name = M.get_ns_name(review) if kind_defined else M._MISSING
    ns_name_id = (
        vocab.str_id(ns_name) if isinstance(ns_name, str) else _UNDEF
    )

    obj = M.get_default(review, "object", {})
    old = M.get_default(review, "oldObject", {})
    obj_present = obj != {}
    old_present = old != {}

    if is_ns:
        # matches_nsselector for Namespace reviews routes through
        # any_labelselector_match over the object/oldObject labels — the
        # kernel reuses obj_labels/old_labels with the same 4-case logic,
        # so nssel_labels is unused here
        nssel_defined = True
        nssel_labels = []
        nssel_empty = False
    else:
        # matches_nsselector's non-Namespace clause hoists input.review.kind
        # into `not is_ns(...)`, so an undefined kind fails it outright
        cands = (
            M.get_ns_candidates(review, ns_cache) if kind_defined else []
        )
        nssel_defined = bool(cands)
        nssel_labels = []
        nssel_empty = False
        for cand in cands:
            meta = M.get_default(cand, "metadata", {})
            pairs = _label_pairs(M.get_default(meta, "labels", {}), vocab)
            if pairs and not nssel_labels:
                nssel_labels = pairs
            elif not pairs:
                nssel_empty = True

    return ReviewFeatures(
        group_id=vocab.str_id(group) if isinstance(group, str) else _UNDEF,
        kind_id=vocab.str_id(kind) if isinstance(kind, str) else _UNDEF,
        kind_defined=kind_defined,
        is_ns=is_ns,
        has_namespace=has_namespace,
        ns_name_id=ns_name_id,
        obj_present=obj_present,
        old_present=old_present,
        obj_labels=_label_pairs(_obj_labels(obj), vocab),
        old_labels=_label_pairs(_obj_labels(old), vocab),
        nssel_defined=nssel_defined,
        nssel_labels=nssel_labels,
        nssel_empty=nssel_empty if not is_ns else False,
    )


@dataclass
class FeatureBatch:
    """Stacked ReviewFeatures: arrays of shape [N] / [N, ML, 2]."""

    group_id: np.ndarray
    kind_id: np.ndarray
    kind_defined: np.ndarray
    is_ns: np.ndarray
    has_namespace: np.ndarray
    ns_name_id: np.ndarray
    obj_present: np.ndarray
    old_present: np.ndarray
    obj_labels: np.ndarray  # [N, ML, 2], -1 pad
    old_labels: np.ndarray
    nssel_defined: np.ndarray
    nssel_labels: np.ndarray
    nssel_empty: np.ndarray
    # [N] true when an explicit max_labels truncated any of this review's
    # label rows — truncated selectors can falsely miss; callers must
    # route flagged rows to the oracle path
    label_overflow: np.ndarray = None

    @property
    def n(self) -> int:
        return int(self.group_id.shape[0])


def _stack_labels(rows: List[List[Tuple[int, int]]], ml: int) -> np.ndarray:
    out = np.full((len(rows), ml, 2), -1, np.int32)
    for i, row in enumerate(rows):
        for j, (k, v) in enumerate(row[:ml]):
            out[i, j, 0] = k
            out[i, j, 1] = v
    return out


def batch_review_features(
    feats: Sequence[ReviewFeatures], max_labels: Optional[int] = None
) -> FeatureBatch:
    longest = max(
        (
            max(len(f.obj_labels), len(f.old_labels), len(f.nssel_labels))
            for f in feats
        ),
        default=1,
    )
    ml = max_labels if max_labels is not None else _bucket(max(longest, 1), lo=4)
    label_overflow = np.array(
        [
            max(len(f.obj_labels), len(f.old_labels), len(f.nssel_labels)) > ml
            for f in feats
        ],
        bool,
    )
    return FeatureBatch(
        group_id=np.array([f.group_id for f in feats], np.int32),
        kind_id=np.array([f.kind_id for f in feats], np.int32),
        kind_defined=np.array([f.kind_defined for f in feats], bool),
        is_ns=np.array([f.is_ns for f in feats], bool),
        has_namespace=np.array([f.has_namespace for f in feats], bool),
        ns_name_id=np.array([f.ns_name_id for f in feats], np.int32),
        obj_present=np.array([f.obj_present for f in feats], bool),
        old_present=np.array([f.old_present for f in feats], bool),
        obj_labels=_stack_labels([f.obj_labels for f in feats], ml),
        old_labels=_stack_labels([f.old_labels for f in feats], ml),
        nssel_defined=np.array([f.nssel_defined for f in feats], bool),
        nssel_labels=_stack_labels([f.nssel_labels for f in feats], ml),
        nssel_empty=np.array([f.nssel_empty for f in feats], bool),
        label_overflow=label_overflow,
    )
