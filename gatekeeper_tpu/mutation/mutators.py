"""The three mutator kinds and their application semantics.

Models Gatekeeper's mutation CRDs (mutations.gatekeeper.sh):

  * `Assign` — set a value at a location outside `metadata`; honors
    `spec.applyTo` GVK filters, `spec.match` (the SAME match schema as
    constraints — screened by the vectorized match kernel),
    `spec.parameters.pathTests` (MustExist / MustNotExist guards), and
    `spec.parameters.assignIf` (`in` / `notIn` tests on the current
    value).
  * `AssignMetadata` — set `metadata.labels.<key>` or
    `metadata.annotations.<key>`, NEVER overwriting an existing value
    (the reference's add-if-absent semantics make it trivially
    idempotent).
  * `ModifySet` — merge or prune scalar members of a list at the
    location; merge appends missing values in declaration order, prune
    removes matching members.

Application is side-effect free: `apply(obj, review)` returns
(new_obj, changed) and never mutates its input — the fixpoint engine in
`system.py` depends on that to detect convergence.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .path import Node, ObjectNode, PathError, parse_path

MUTATION_GROUP = "mutations.gatekeeper.sh"
MUTATOR_KINDS = ("Assign", "AssignMetadata", "ModifySet")

# fields under metadata that AssignMetadata may target
_METADATA_MAPS = ("labels", "annotations")


class MutatorError(ValueError):
    """Invalid mutator spec (ingestion-time rejection)."""


class MutationApplyError(RuntimeError):
    """A mutator hit an incompatibly-typed node while applying — the
    object is left unmodified and the request must NOT be admitted
    half-mutated."""


class ConvergenceError(RuntimeError):
    """The mutator set failed to reach a fixpoint within the iteration
    cap; the object is never admitted in this state."""


def _meta_name(obj: Dict[str, Any]) -> str:
    return ((obj.get("metadata") or {}).get("name")) or "?"


class Mutator:
    """Common base: identity, match spec, applyTo filter, location."""

    kind: str = "?"

    def __init__(self, obj: Dict[str, Any]):
        if not isinstance(obj, dict):
            raise MutatorError("mutator is not an object")
        self.name = _meta_name(obj)
        if self.name == "?":
            raise MutatorError(f"{self.kind} has no metadata.name")
        self.obj = copy.deepcopy(obj)
        spec = obj.get("spec")
        if not isinstance(spec, dict):
            raise MutatorError(f"{self.kind} {self.name} has no spec")
        self.match: Dict[str, Any] = (
            spec.get("match") if isinstance(spec.get("match"), dict) else {}
        )
        location = spec.get("location")
        if not isinstance(location, str):
            raise MutatorError(
                f"{self.kind} {self.name} has no spec.location"
            )
        try:
            self.path: Tuple[Node, ...] = parse_path(location)
        except PathError as e:
            raise MutatorError(f"{self.kind} {self.name}: {e}") from e
        self.location = location
        self.apply_to = self._parse_apply_to(spec)
        self.params: Dict[str, Any] = (
            spec.get("parameters")
            if isinstance(spec.get("parameters"), dict)
            else {}
        )

    # -- identity ------------------------------------------------------------

    @property
    def id(self) -> str:
        return f"{self.kind}/{self.name}"

    def sort_key(self) -> Tuple[str, str]:
        """Total order independent of ingestion order (the reference
        sorts by mutator id the same way, mutation/system.go)."""
        return (self.kind, self.name)

    # -- applicability -------------------------------------------------------

    def _parse_apply_to(self, spec: Dict[str, Any]):
        raw = spec.get("applyTo")
        if raw is None:
            return None  # AssignMetadata: applies to every GVK
        if not isinstance(raw, list) or not raw:
            raise MutatorError(
                f"{self.kind} {self.name}: applyTo must be a non-empty list"
            )
        out = []
        for entry in raw:
            if not isinstance(entry, dict):
                raise MutatorError(
                    f"{self.kind} {self.name}: applyTo entries must be objects"
                )
            out.append(
                (
                    list(entry.get("groups") or []),
                    list(entry.get("versions") or []),
                    list(entry.get("kinds") or []),
                )
            )
        return out

    def applies_to(self, group: str, version: str, kind: str) -> bool:
        if self.apply_to is None:
            return True
        for groups, versions, kinds in self.apply_to:
            if (
                ("*" in groups or group in groups)
                and ("*" in versions or version in versions)
                and ("*" in kinds or kind in kinds)
            ):
                return True
        return False

    # -- application ---------------------------------------------------------

    def apply(self, obj: Any, review: Dict[str, Any]) -> Tuple[Any, bool]:
        """-> (new object, changed). Never mutates `obj` in place."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# traversal


def _walk_existing(obj: Any, nodes: Sequence[Node]) -> List[Any]:
    """Values reachable at `nodes` in `obj` (globs fan out); [] when the
    path does not resolve. Type mismatches resolve to nothing — this is
    the read side (pathTests / assignIf), which must not raise."""
    frontier = [obj]
    for n in nodes:
        nxt: List[Any] = []
        for cur in frontier:
            if not isinstance(cur, dict) or n.name not in cur:
                continue
            val = cur[n.name]
            if isinstance(n, ObjectNode):
                nxt.append(val)
            else:
                if not isinstance(val, list):
                    continue
                for el in val:
                    if not isinstance(el, dict) or n.key_field not in el:
                        continue
                    if n.glob or el[n.key_field] == n.key_value:
                        nxt.append(el)
        frontier = nxt
        if not frontier:
            break
    return frontier


def _set_path(
    obj: Any, nodes: Sequence[Node], setter, who: str, create: bool = True
) -> Tuple[Any, bool]:
    """Copy-on-write traversal: returns (new_obj, changed).

    `setter(container, key) -> bool` runs at every terminal container
    (parent dict) the path resolves to, mutating the (already copied)
    container in place and reporting whether it changed anything.
    Missing intermediate objects and keyed list elements are created
    when `create`; globs never create. A node that exists with an
    incompatible type raises MutationApplyError — mutating through it
    would corrupt the object.
    """
    if not isinstance(obj, dict):
        raise MutationApplyError(f"{who}: object root is not a map")

    def rec(cur: Dict[str, Any], i: int) -> Tuple[Dict[str, Any], bool]:
        node = nodes[i]
        last = i == len(nodes) - 1
        out = dict(cur)
        if isinstance(node, ObjectNode):
            if last:
                changed = setter(out, node.name)
                return (out, True) if changed else (cur, False)
            child = cur.get(node.name)
            if child is None and node.name not in cur:
                if not create:
                    return cur, False
                child = {}
            if not isinstance(child, dict):
                raise MutationApplyError(
                    f"{who}: {node.name} exists but is not an object"
                )
            new_child, changed = rec(child, i + 1)
            if not changed:
                return cur, False
            out[node.name] = new_child
            return out, True
        # ListNode
        child = cur.get(node.name)
        if child is None and node.name not in cur:
            if not create or node.glob:
                return cur, False
            child = []
        if not isinstance(child, list):
            raise MutationApplyError(
                f"{who}: {node.name} exists but is not a list"
            )
        new_list = list(child)
        changed_any = False
        matched = False
        for j, el in enumerate(new_list):
            if not isinstance(el, dict) or node.key_field not in el:
                continue
            if node.glob or el[node.key_field] == node.key_value:
                matched = True
                if last:
                    el2 = dict(el)
                    if setter(el2, None):
                        new_list[j] = el2
                        changed_any = True
                else:
                    el2, ch = rec(el, i + 1)
                    if ch:
                        new_list[j] = el2
                        changed_any = True
        if not matched and not node.glob and create:
            # keyed element missing: create it (Gatekeeper adds the
            # element with its key field set, then mutates into it)
            el: Dict[str, Any] = {node.key_field: node.key_value}
            if last:
                setter(el, None)
            else:
                el, _ = rec(el, i + 1)
            new_list.append(el)
            changed_any = True
        if not changed_any:
            return cur, False
        out[node.name] = new_list
        return out, True

    return rec(obj, 0)


# ---------------------------------------------------------------------------
# pathTests / assignIf


def _check_path_tests(mut: Mutator, obj: Any) -> bool:
    tests = mut.params.get("pathTests")
    if not isinstance(tests, list):
        return True
    for t in tests:
        if not isinstance(t, dict):
            continue
        sub = t.get("subPath")
        cond = t.get("condition")
        if not isinstance(sub, str):
            continue
        try:
            nodes = parse_path(sub)
        except PathError:
            return False
        exists = bool(_walk_existing(obj, nodes))
        if cond == "MustExist" and not exists:
            return False
        if cond == "MustNotExist" and exists:
            return False
    return True


_ABSENT = object()


def _assign_if_ok(assign_if: Any, current: Any) -> bool:
    """`assignIf: {in: [...], notIn: [...]}` against the current value
    at the location (absent compares equal only to an explicit null in
    `in`; absent trivially passes `notIn`)."""
    if not isinstance(assign_if, dict):
        return True
    inn = assign_if.get("in")
    if isinstance(inn, list):
        if current is _ABSENT:
            if None not in inn:
                return False
        elif not any(current == v for v in inn):
            return False
    not_in = assign_if.get("notIn")
    if isinstance(not_in, list) and current is not _ABSENT:
        if any(current == v for v in not_in):
            return False
    return True


# ---------------------------------------------------------------------------
# the three kinds


class AssignMutator(Mutator):
    kind = "Assign"

    def __init__(self, obj: Dict[str, Any]):
        super().__init__(obj)
        if self.apply_to is None:
            raise MutatorError(
                f"Assign {self.name}: spec.applyTo is required"
            )
        if isinstance(self.path[0], ObjectNode) and (
            self.path[0].name == "metadata"
        ):
            raise MutatorError(
                f"Assign {self.name}: cannot mutate metadata "
                "(use AssignMetadata)"
            )
        assign = self.params.get("assign")
        if not isinstance(assign, dict) or "value" not in assign:
            raise MutatorError(
                f"Assign {self.name}: spec.parameters.assign.value is required"
            )
        self.value = assign["value"]
        self.assign_if = self.params.get("assignIf")

    def apply(self, obj: Any, review: Dict[str, Any]) -> Tuple[Any, bool]:
        if not _check_path_tests(self, obj):
            return obj, False

        value = self.value

        def setter(container: Dict[str, Any], key: Optional[str]) -> bool:
            if key is None:
                # terminal inside a keyed list element: value must be an
                # object merged over the element? The reference forbids
                # list-terminal Assign without a field; treat the whole
                # element as the slot via its key field — unsupported.
                raise MutationApplyError(
                    f"Assign {self.name}: location terminates inside a "
                    "list element; address a field of the element"
                )
            current = container[key] if key in container else _ABSENT
            if not _assign_if_ok(self.assign_if, current):
                return False
            if current is not _ABSENT and container[key] == value:
                return False
            container[key] = copy.deepcopy(value)
            return True

        return _set_path(obj, self.path, setter, self.id)


class AssignMetadataMutator(Mutator):
    kind = "AssignMetadata"

    def __init__(self, obj: Dict[str, Any]):
        super().__init__(obj)
        ok = (
            len(self.path) == 3
            and all(isinstance(n, ObjectNode) for n in self.path)
            and self.path[0].name == "metadata"
            and self.path[1].name in _METADATA_MAPS
        )
        if not ok:
            raise MutatorError(
                f"AssignMetadata {self.name}: location must be "
                "metadata.labels.<key> or metadata.annotations.<key>"
            )
        assign = self.params.get("assign")
        if not isinstance(assign, dict) or not isinstance(
            assign.get("value"), str
        ):
            raise MutatorError(
                f"AssignMetadata {self.name}: spec.parameters.assign.value "
                "must be a string"
            )
        self.value = assign["value"]

    def apply(self, obj: Any, review: Dict[str, Any]) -> Tuple[Any, bool]:
        def setter(container: Dict[str, Any], key: Optional[str]) -> bool:
            if key in container:
                return False  # never overwrite (reference semantics)
            container[key] = self.value
            return True

        return _set_path(obj, self.path, setter, self.id)


class ModifySetMutator(Mutator):
    kind = "ModifySet"

    def __init__(self, obj: Dict[str, Any]):
        super().__init__(obj)
        if self.apply_to is None:
            raise MutatorError(
                f"ModifySet {self.name}: spec.applyTo is required"
            )
        op = self.params.get("operation", "merge")
        if op not in ("merge", "prune"):
            raise MutatorError(
                f"ModifySet {self.name}: operation must be merge|prune, "
                f"got {op!r}"
            )
        self.operation = op
        values = self.params.get("values")
        from_list = values.get("fromList") if isinstance(values, dict) else None
        if not isinstance(from_list, list) or not from_list:
            raise MutatorError(
                f"ModifySet {self.name}: spec.parameters.values.fromList "
                "must be a non-empty list"
            )
        self.values = from_list

    def apply(self, obj: Any, review: Dict[str, Any]) -> Tuple[Any, bool]:
        if not _check_path_tests(self, obj):
            return obj, False

        def setter(container: Dict[str, Any], key: Optional[str]) -> bool:
            if key is None:
                raise MutationApplyError(
                    f"ModifySet {self.name}: location terminates inside a "
                    "list element; address a field of the element"
                )
            cur = container.get(key)
            if cur is None and key not in container:
                if self.operation == "prune":
                    return False
                cur = []
            if not isinstance(cur, list):
                raise MutationApplyError(
                    f"ModifySet {self.name}: {key} exists but is not a list"
                )
            if self.operation == "merge":
                missing = [v for v in self.values if v not in cur]
                if not missing:
                    return False
                container[key] = list(cur) + [
                    copy.deepcopy(v) for v in missing
                ]
                return True
            kept = [v for v in cur if v not in self.values]
            if len(kept) == len(cur):
                return False
            container[key] = kept
            return True

        # prune must not create the list it would prune from
        return _set_path(
            obj, self.path, setter, self.id,
            create=self.operation == "merge",
        )


_KIND_CLASSES = {
    "Assign": AssignMutator,
    "AssignMetadata": AssignMetadataMutator,
    "ModifySet": ModifySetMutator,
}


def mutator_from_obj(obj: Dict[str, Any]) -> Mutator:
    """Build a typed mutator from its CR dict (raises MutatorError)."""
    if not isinstance(obj, dict):
        raise MutatorError("mutator is not an object")
    kind = obj.get("kind")
    cls = _KIND_CLASSES.get(kind)
    if cls is None:
        raise MutatorError(
            f"unknown mutator kind {kind!r} (known: {MUTATOR_KINDS})"
        )
    group = (obj.get("apiVersion") or "").partition("/")[0]
    if group != MUTATION_GROUP:
        raise MutatorError(
            f"{kind} {_meta_name(obj)} has the wrong group: {group!r}"
        )
    return cls(obj)
