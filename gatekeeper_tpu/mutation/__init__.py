"""Mutation subsystem: Assign / AssignMetadata / ModifySet.

The second admission plane (mutate-then-validate), modeled on
Gatekeeper v3's mutation CRDs (pkg/mutation/ in the reference tree —
the survey pins this reproduction at "pre-mutation" v3, so this package
is the capability gap closed natively). Pieces:

  * `path`     — the location-path grammar (`spec.containers[name:*].
                 image`): list globs, key-field addressing, quoting.
  * `mutators` — the three mutator kinds with Gatekeeper's semantics
                 (AssignMetadata never overwrites; Assign honors
                 pathTests + assignIf; ModifySet merges/prunes list
                 members).
  * `system`   — ingestion-order-independent mutator registry with the
                 schema-conflict detector, the kernel-backed batch
                 screen (`match_matrix` reuse), and the fixpoint
                 application engine (hard iteration cap; a
                 non-converged object is NEVER admitted).
  * `patch`    — RFC 6902 JSONPatch rendering (before/after diff) for
                 the `/v1/mutate` webhook responses.
  * `lint`     — offline GK-M0xx diagnostics shared by the analysis
                 CLI's `mutators` mode and the controllers.
"""

from .path import PathError, parse_path, render_path  # noqa: F401
from .mutators import (  # noqa: F401
    MUTATION_GROUP,
    MUTATOR_KINDS,
    ConvergenceError,
    MutationApplyError,
    MutatorError,
    mutator_from_obj,
)
from .patch import json_patch  # noqa: F401
from .system import MutationSystem  # noqa: F401
