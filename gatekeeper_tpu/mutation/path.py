"""Location-path grammar for mutators.

Gatekeeper's mutation location syntax (pkg/mutation/path/parser):

    spec.template.spec.containers[name: *].image
    spec.containers[name: "sidecar"].securityContext
    metadata.labels."my.dotted/key"

  * `.`-separated object segments; a segment may be double-quoted to
    carry dots, brackets, or spaces literally;
  * `[key: value]` addresses a LIST whose elements are objects keyed by
    `key`; `value` may be `*` (glob: every element with the key field),
    a bare token, or a double-quoted string;
  * the key field and value tolerate surrounding whitespace.

Parsed form: a tuple of nodes — `ObjectNode(name)` for field access,
`ListNode(name, key_field, key_value, glob)` for keyed list access.
The node types double as the schema the conflict detector compares:
a `ListNode` asserts its field is a list; an `ObjectNode` that is not
the final node asserts its field is an object.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple, Union


class PathError(ValueError):
    """Malformed location path (position-annotated message)."""


@dataclass(frozen=True)
class ObjectNode:
    name: str


@dataclass(frozen=True)
class ListNode:
    name: str
    key_field: str
    key_value: Optional[str]  # None when glob
    glob: bool


Node = Union[ObjectNode, ListNode]


def _err(path: str, pos: int, why: str) -> PathError:
    return PathError(f"invalid location {path!r} at offset {pos}: {why}")


def _read_token(path: str, i: int, stop: str) -> Tuple[str, int]:
    """Read a quoted string or a bare token ending at any char in
    `stop` (exclusive). Returns (text, next index)."""
    if i < len(path) and path[i] == '"':
        j = i + 1
        out = []
        while j < len(path):
            c = path[j]
            if c == "\\" and j + 1 < len(path):
                out.append(path[j + 1])
                j += 2
                continue
            if c == '"':
                return "".join(out), j + 1
            out.append(c)
            j += 1
        raise _err(path, i, "unterminated quote")
    j = i
    while j < len(path) and path[j] not in stop:
        j += 1
    return path[i:j], j


def parse_path(path: str) -> Tuple[Node, ...]:
    """Parse a location string into its node tuple (raises PathError)."""
    if not isinstance(path, str) or not path.strip():
        raise PathError(f"invalid location {path!r}: empty path")
    path = path.strip()
    nodes: List[Node] = []
    i = 0
    while i < len(path):
        name, i = _read_token(path, i, ".[")
        name = name.strip()
        if not name:
            raise _err(path, i, "empty segment")
        if i < len(path) and path[i] == "[":
            j = path.find("]", i)
            if j < 0:
                raise _err(path, i, "unterminated '['")
            inner = path[i + 1 : j]
            key, k = _read_token(inner, 0, ":")
            if k >= len(inner) or inner[k] != ":":
                raise _err(path, i, "list accessor needs 'key: value'")
            key = key.strip()
            if not key:
                raise _err(path, i, "empty key field")
            value_raw = inner[k + 1 :].strip()
            if value_raw == "*":
                nodes.append(ListNode(name, key, None, glob=True))
            else:
                value, _ = _read_token(value_raw, 0, "")
                value = value if value_raw.startswith('"') else value.strip()
                if not value:
                    raise _err(path, i, "empty key value")
                nodes.append(ListNode(name, key, value, glob=False))
            i = j + 1
        else:
            nodes.append(ObjectNode(name))
        if i < len(path):
            if path[i] != ".":
                raise _err(path, i, f"expected '.' before {path[i]!r}")
            i += 1
            if i >= len(path):
                raise _err(path, i, "trailing '.'")
    if not nodes:
        raise PathError(f"invalid location {path!r}: empty path")
    return tuple(nodes)


def _quote_seg(s: str) -> str:
    if s and all(c not in '."[]: \\' for c in s):
        return s
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def render_path(nodes: Tuple[Node, ...]) -> str:
    """Canonical string form of a parsed path (parse ∘ render = id)."""
    out = []
    for n in nodes:
        if isinstance(n, ListNode):
            val = "*" if n.glob else _quote_seg(n.key_value)
            out.append(f"{_quote_seg(n.name)}[{_quote_seg(n.key_field)}: {val}]")
        else:
            out.append(_quote_seg(n.name))
    return ".".join(out)
