"""Mutator registry + schema-conflict detection + batch screening +
fixpoint application.

`MutationSystem` is the mutation plane's Client-equivalent: controllers
upsert/remove mutator CRs into it, the webhook screens and applies
through it. Key properties:

  * **Ingestion-order independence** — mutators apply in (kind, name)
    sort order, so two pods that ingested the same set in different
    orders produce byte-identical mutations.
  * **Schema conflicts** — two mutators whose location paths imply
    different node types for the same tree position (object vs list,
    or lists keyed by different fields) are BOTH quarantined: neither
    applies until the conflict clears (the reference's
    schema.ErrConflictingSchema semantics).
  * **Kernel-screened batches** — `screen(reviews)` computes the full
    [n_mutators, n_reviews] applicability matrix with ONE
    `engine.matchkernel.match_matrix` device dispatch (mutator Match
    specs reuse the constraint match schema end-to-end:
    `constraint/match.py` semantics → `flatten/encoder.py` features →
    the jitted kernel). Rows whose label features overflowed the batch
    bucket re-check on the host oracle, so truncation can't flip a
    verdict.
  * **Fixpoint with a hard cap** — `apply` re-runs the applicable
    mutator list until a full pass changes nothing; past
    MAX_ITERATIONS it raises ConvergenceError. A non-converged object
    is NEVER admitted (the webhook turns the error into a 500).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..flatten.encoder import batch_review_features
from ..flatten.vocab import Vocab
from .mutators import ConvergenceError, Mutator, mutator_from_obj
from .path import ListNode

# fixpoint cap: the reference uses 3 System.Mutate iterations over an
# already-sorted list; a deeper cap keeps legitimately-chained mutators
# (A enables B's pathTest...) converging while still bounding cycles
MAX_ITERATIONS = 16


def _schema_conflicts(muts: Sequence[Mutator]) -> Dict[str, List[str]]:
    """{mutator id -> sorted conflicting ids}. Two mutators conflict
    when their location trees disagree on a node's type: one addresses
    `x.y` as an object (intermediate ObjectNode) where the other
    addresses it as a list (`x.y[k: v]`), or both address it as a list
    but keyed by different fields. Terminal nodes are type-Unknown and
    conflict with nothing."""
    # position key: tuple of (name, kind-tag) steps from the root
    implied: Dict[Tuple, Dict[str, List[str]]] = {}
    for m in muts:
        key: Tuple = ()
        for i, node in enumerate(m.path):
            last = i == len(m.path) - 1
            key = key + (node.name,)
            if isinstance(node, ListNode):
                ty = f"list[{node.key_field}]"
            elif last:
                ty = None  # terminal object node: type unknown
            else:
                ty = "object"
            if ty is not None:
                implied.setdefault(key, {}).setdefault(ty, []).append(m.id)
            key = key + (ty or "*",)
    out: Dict[str, List[str]] = {}
    for _pos, by_type in implied.items():
        if len(by_type) < 2:
            continue
        all_ids = sorted({i for ids in by_type.values() for i in ids})
        for ty, ids in by_type.items():
            for mid in ids:
                others = [o for o in all_ids if o != mid]
                if others:
                    cur = out.setdefault(mid, [])
                    for o in others:
                        if o not in cur:
                            cur.append(o)
    return {k: sorted(v) for k, v in out.items()}


def _review_gvk(review: Dict[str, Any]) -> Tuple[str, str, str]:
    k = review.get("kind") if isinstance(review, dict) else None
    if not isinstance(k, dict):
        return ("", "", "")
    return (
        k.get("group") or "",
        k.get("version") or "",
        k.get("kind") or "",
    )


class MutationSystem:
    def __init__(self, metrics=None, logger=None, target_handler=None):
        from ..constraint.handler import default_handler
        from ..logs import null_logger

        self.metrics = metrics
        self.log = logger if logger is not None else null_logger()
        # the target whose review/match vocabulary mutator Match specs
        # speak: K8s by default; an AgentActionTarget makes this system
        # rewrite tool-call arguments instead of pods (docs/targets.md)
        self.target_handler = (
            target_handler if target_handler is not None else default_handler()
        )
        self._lock = threading.Lock()
        self._mutators: Dict[str, Mutator] = {}  # id -> mutator
        self._conflicts: Dict[str, List[str]] = {}
        self._generation = 0
        # screening caches, rebuilt lazily per generation
        self._vocab = Vocab()
        self._spec_cache: Optional[Tuple[int, List[Mutator], dict]] = None
        self.screen_dispatches = 0

    # -- registry ------------------------------------------------------------

    def upsert(self, obj: Dict[str, Any]) -> Mutator:
        """Ingest (or replace) a mutator CR; raises MutatorError on an
        invalid spec. Recomputes the conflict set."""
        mut = mutator_from_obj(obj)
        with self._lock:
            self._mutators[mut.id] = mut
            self._rebuild_locked()
        return mut

    def remove(self, obj_or_id) -> None:
        if isinstance(obj_or_id, str):
            mid = obj_or_id
        else:
            kind = (obj_or_id or {}).get("kind", "?")
            name = ((obj_or_id or {}).get("metadata") or {}).get("name", "?")
            mid = f"{kind}/{name}"
        with self._lock:
            if self._mutators.pop(mid, None) is not None:
                self._rebuild_locked()

    def wipe(self) -> None:
        """Drop every mutator (Config wipe/replay: the watch replay
        re-upserts the live set)."""
        with self._lock:
            if self._mutators:
                self._mutators = {}
                self._rebuild_locked()

    def _rebuild_locked(self) -> None:
        self._generation += 1
        self._spec_cache = None
        self._conflicts = _schema_conflicts(
            sorted(self._mutators.values(), key=Mutator.sort_key)
        )

    def ordered(self) -> List[Mutator]:
        """Active (non-conflicted) mutators in application order."""
        with self._lock:
            return [
                m
                for m in sorted(
                    self._mutators.values(), key=Mutator.sort_key
                )
                if m.id not in self._conflicts
            ]

    def conflicts(self) -> Dict[str, List[str]]:
        with self._lock:
            return {k: list(v) for k, v in self._conflicts.items()}

    def count(self) -> int:
        with self._lock:
            return len(self._mutators)

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    # -- screening -----------------------------------------------------------

    def _specs(self) -> Tuple[List[Mutator], Optional[dict]]:
        """(ordered mutators, device-ready match tensors) for the
        current generation; tensors cached until the set changes."""
        from ..engine.matchkernel import matchspec_to_device

        with self._lock:
            gen = self._generation
            if self._spec_cache is not None and self._spec_cache[0] == gen:
                _, muts, ms = self._spec_cache
                return muts, ms
            muts = [
                m
                for m in sorted(
                    self._mutators.values(), key=Mutator.sort_key
                )
                if m.id not in self._conflicts
            ]
            if not muts:
                self._spec_cache = (gen, [], None)
                return [], None
            specs = self.target_handler.compile_match_specs(
                [{"spec": {"match": m.match}} for m in muts], self._vocab
            )
            ms = matchspec_to_device(specs)
            self._spec_cache = (gen, muts, ms)
            return muts, ms

    def screen(
        self,
        reviews: Sequence[Dict[str, Any]],
        ns_cache: Optional[Dict[str, Any]] = None,
    ) -> Tuple[List[Mutator], np.ndarray]:
        """One device dispatch for the whole batch: returns the ordered
        mutator snapshot and the [n_mutators, n_reviews] bool matrix of
        (match ∧ applyTo) applicability."""
        from ..engine.matchkernel import features_to_device, match_matrix
        from ..flatten.vocab import OverlayVocab

        ns_cache = ns_cache or {}
        muts, ms = self._specs()
        if not muts or not reviews:
            return muts, np.zeros((len(muts), len(reviews)), bool)
        # ephemeral overlay: every batch carries fresh names/labels;
        # interning them into the persistent vocab would grow it (and
        # re-key the spec tensors' id space) forever. Novel strings get
        # local ids >= base_len, which can never equal a compiled spec
        # id — exactly the "never matches" semantics they need.
        overlay = OverlayVocab(self._vocab)
        feats = [
            self.target_handler.encode_review_features(r, ns_cache, overlay)
            for r in reviews
        ]
        fb = batch_review_features(feats)
        mat = np.asarray(
            match_matrix(ms, features_to_device(fb))
        ).astype(bool)
        self.screen_dispatches += 1
        if self.metrics is not None:
            self.metrics.record("mutation_screen_dispatch_total", 1)
        # truncated label rows can falsely miss: re-verdict on the oracle
        overflow = getattr(fb, "label_overflow", None)
        if overflow is not None and overflow.any():
            for i in np.flatnonzero(overflow):
                mat[:, i] = self._screen_host_one(muts, reviews[i], ns_cache)
        self._and_apply_to(muts, reviews, mat)
        return muts, mat

    def screen_host(
        self,
        reviews: Sequence[Dict[str, Any]],
        ns_cache: Optional[Dict[str, Any]] = None,
    ) -> Tuple[List[Mutator], np.ndarray]:
        """Pure-host fallback screen (oracle semantics, no device)."""
        ns_cache = ns_cache or {}
        muts = self.ordered()
        mat = np.zeros((len(muts), len(reviews)), bool)
        for i, r in enumerate(reviews):
            mat[:, i] = self._screen_host_one(muts, r, ns_cache)
        self._and_apply_to(muts, reviews, mat)
        return muts, mat

    def _screen_host_one(
        self,
        muts: Sequence[Mutator],
        review: Dict[str, Any],
        ns_cache: Dict[str, Any],
    ) -> np.ndarray:
        return np.array(
            [
                self.target_handler.matches_constraint(
                    {"spec": {"match": m.match}}, review, ns_cache
                )
                for m in muts
            ],
            bool,
        )

    @staticmethod
    def _and_apply_to(muts, reviews, mat: np.ndarray) -> None:
        """AND the host-side applyTo GVK filter into the match matrix
        (exact small-set membership — not worth a device round trip)."""
        gvks = [_review_gvk(r) for r in reviews]
        for j, m in enumerate(muts):
            if m.apply_to is None:
                continue
            for i, (g, v, k) in enumerate(gvks):
                if mat[j, i] and not m.applies_to(g, v, k):
                    mat[j, i] = False

    # -- application ---------------------------------------------------------

    def apply(
        self,
        obj: Dict[str, Any],
        review: Dict[str, Any],
        muts: Optional[Sequence[Mutator]] = None,
    ) -> Tuple[Dict[str, Any], int]:
        """Fixpoint application of `muts` (already screened; defaults
        to every active mutator) -> (mutated object, iterations). The
        input object is never modified. Raises ConvergenceError past
        MAX_ITERATIONS — callers must NOT admit the object then."""
        if muts is None:
            muts = self.ordered()
        cur = obj
        for iteration in range(1, MAX_ITERATIONS + 1):
            changed_ids: List[str] = []
            for m in muts:
                cur, changed = m.apply(cur, review)
                if changed:
                    changed_ids.append(m.id)
            if not changed_ids:
                return cur, iteration
        raise ConvergenceError(
            f"mutation did not converge after {MAX_ITERATIONS} iterations; "
            f"still changing: {sorted(set(changed_ids))}"
        )

    # -- introspection -------------------------------------------------------

    def report_gauges(self) -> None:
        """Publish the registry-shape gauges (mutators per kind/status,
        conflict count) — called by the mutator controller after every
        ingest/remove so dashboards track the live set."""
        if self.metrics is None:
            return
        with self._lock:
            by_kind: Dict[Tuple[str, str], int] = {}
            for m in self._mutators.values():
                status = (
                    "conflict" if m.id in self._conflicts else "active"
                )
                by_kind[(m.kind, status)] = (
                    by_kind.get((m.kind, status), 0) + 1
                )
            n_conf = len(self._conflicts)
        from .mutators import MUTATOR_KINDS

        for kind in MUTATOR_KINDS:
            for status in ("active", "conflict"):
                self.metrics.gauge(
                    "mutators",
                    by_kind.get((kind, status), 0),
                    kind=kind,
                    status=status,
                )
        self.metrics.gauge("mutator_conflicts", n_conf)
