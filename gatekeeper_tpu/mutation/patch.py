"""RFC 6902 JSONPatch rendering: before/after object diff.

The `/v1/mutate` webhook answers with a patch, not the mutated object
(the apiserver applies the patch itself), so the mutation engine's
output must be rendered as add/replace/remove operations. The diff is
structural and minimal-ish: dicts recurse per key, lists recurse per
index when same-length, extend with `add` ops when the original is a
prefix, truncate with end-first `remove` ops when the result is a
prefix, and fall back to a whole-list `replace` otherwise (apiserver
JSONPatch application is positional, so index-precise ops matter more
than op-count minimality).
"""

from __future__ import annotations

from typing import Any, Dict, List


def escape_pointer(seg: str) -> str:
    """RFC 6901 token escaping."""
    return str(seg).replace("~", "~0").replace("/", "~1")


def json_patch(before: Any, after: Any) -> List[Dict[str, Any]]:
    """RFC 6902 ops transforming `before` into `after` (empty when
    equal). Ops are emitted in application order — removes within one
    list come highest-index-first so earlier ops don't shift the
    indices later ones target."""
    ops: List[Dict[str, Any]] = []
    _diff(before, after, "", ops)
    return ops


def _diff(before: Any, after: Any, path: str, ops: List[Dict[str, Any]]):
    if before == after and type(before) is type(after):
        return
    if isinstance(before, dict) and isinstance(after, dict):
        for k in before:
            if k not in after:
                ops.append(
                    {"op": "remove", "path": f"{path}/{escape_pointer(k)}"}
                )
        for k, v in after.items():
            sub = f"{path}/{escape_pointer(k)}"
            if k not in before:
                ops.append({"op": "add", "path": sub, "value": v})
            else:
                _diff(before[k], v, sub, ops)
        return
    if isinstance(before, list) and isinstance(after, list):
        nb, na = len(before), len(after)
        if na >= nb and before == after[:nb]:
            for i in range(nb, na):
                ops.append(
                    {"op": "add", "path": f"{path}/{i}", "value": after[i]}
                )
            return
        if nb > na and after == before[:na]:
            for i in range(nb - 1, na - 1, -1):
                ops.append({"op": "remove", "path": f"{path}/{i}"})
            return
        if nb == na:
            for i in range(nb):
                _diff(before[i], after[i], f"{path}/{i}", ops)
            return
        ops.append({"op": "replace", "path": path, "value": after})
        return
    ops.append({"op": "replace", "path": path, "value": after})


def apply_patch(obj: Any, ops: List[Dict[str, Any]]) -> Any:
    """Minimal RFC 6902 applier (add/replace/remove) — used by tests
    and the offline lint to verify rendered patches round-trip; NOT a
    full implementation (no move/copy/test)."""
    import copy as _copy
    import json as _json

    out = _copy.deepcopy(obj)
    for op in ops:
        path = op["path"]
        if path == "":
            out = _copy.deepcopy(op["value"])
            continue
        segs = [
            s.replace("~1", "/").replace("~0", "~")
            for s in path.split("/")[1:]
        ]
        parent = out
        for s in segs[:-1]:
            parent = parent[int(s)] if isinstance(parent, list) else parent[s]
        last = segs[-1]
        kind = op["op"]
        if isinstance(parent, list):
            idx = len(parent) if last == "-" else int(last)
            if kind == "add":
                parent.insert(idx, _copy.deepcopy(op["value"]))
            elif kind == "replace":
                parent[idx] = _copy.deepcopy(op["value"])
            elif kind == "remove":
                del parent[idx]
            else:
                raise ValueError(f"unsupported op {kind!r}")
        else:
            if kind == "add" or kind == "replace":
                parent[last] = _copy.deepcopy(op["value"])
            elif kind == "remove":
                del parent[last]
            else:
                raise ValueError(f"unsupported op {kind!r}")
    # normalize away any shared references
    return _json.loads(_json.dumps(out))
