"""Offline mutator diagnostics with stable GK-M0xx codes.

Shared by the analysis CLI's `mutators` mode and available to CI: parse
mutator YAML documents, report per-mutator spec errors and
cross-mutator schema conflicts. Codes are stable contract (like the
analyzer's GK-Vxxx set — docs/mutation.md documents them):

  GK-M001  location path parse error
  GK-M002  missing / non-string spec.location
  GK-M003  AssignMetadata location outside metadata.labels/annotations
  GK-M004  Assign location inside metadata
  GK-M005  invalid parameters (assign.value / values.fromList / operation)
  GK-M006  cross-mutator schema conflict (object-vs-list / key field)
  GK-M007  unknown mutator kind or bad applyTo
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

from .mutators import MutatorError, mutator_from_obj
from .system import _schema_conflicts


@dataclass
class MutatorLint:
    """One mutator's lint outcome."""

    id: str
    source: str = ""
    codes: List[str] = field(default_factory=list)
    messages: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.codes

    def add(self, code: str, message: str) -> None:
        if code not in self.codes:
            self.codes.append(code)
        self.messages.append(f"{code}: {message}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "source": self.source,
            "codes": list(self.codes),
            "messages": list(self.messages),
            "ok": self.ok,
        }

    def render(self) -> str:
        if self.ok:
            return f"{self.id}: OK"
        return f"{self.id}: " + "; ".join(self.messages)


def _classify_error(err: MutatorError) -> str:
    msg = str(err)
    if "invalid location" in msg:
        return "GK-M001"
    if "spec.location" in msg:
        return "GK-M002"
    if "metadata.labels" in msg or "metadata.annotations" in msg:
        return "GK-M003"
    if "cannot mutate metadata" in msg:
        return "GK-M004"
    if (
        "assign.value" in msg
        or "values.fromList" in msg
        or "operation must be" in msg
    ):
        return "GK-M005"
    if "unknown mutator kind" in msg or "applyTo" in msg or "group" in msg:
        return "GK-M007"
    return "GK-M005"


def lint_mutators(
    docs: List[Tuple[str, Dict[str, Any]]],
) -> List[MutatorLint]:
    """[(source, mutator dict)] -> per-mutator lint results, including
    cross-mutator conflict diagnostics over the VALID subset."""
    out: List[MutatorLint] = []
    valid = []
    for source, doc in docs:
        kind = doc.get("kind", "?") if isinstance(doc, dict) else "?"
        name = (
            ((doc.get("metadata") or {}).get("name") or "?")
            if isinstance(doc, dict)
            else "?"
        )
        lint = MutatorLint(id=f"{kind}/{name}", source=source)
        try:
            mut = mutator_from_obj(doc)
        except MutatorError as e:
            lint.add(_classify_error(e), str(e))
            out.append(lint)
            continue
        valid.append((mut, lint))
        out.append(lint)
    conflicts = _schema_conflicts([m for m, _ in valid])
    for mut, lint in valid:
        others = conflicts.get(mut.id)
        if others:
            lint.add(
                "GK-M006",
                f"location schema conflicts with {', '.join(others)}",
            )
    return out


def is_mutator_doc(doc: Any) -> bool:
    from .mutators import MUTATION_GROUP, MUTATOR_KINDS

    return (
        isinstance(doc, dict)
        and doc.get("kind") in MUTATOR_KINDS
        and str(doc.get("apiVersion", "")).startswith(MUTATION_GROUP)
    )
