"""Native host-side components.

`flatten.c` is the C token-flattener (the "host-side JSON->tensor
flattening" native component SURVEY §2 reserves): ~10-20x the pure-
Python encode on big corpora. It is compiled lazily on first use into a
cached shared object (the repo ships source, not binaries); if the
toolchain or compile is unavailable the Python encoder is used —
`encoder.encode_token_table` treats the native path as a strict
drop-in whose outputs are differentially pinned by
tests/test_native_flatten.py.

Set GATEKEEPER_TPU_NO_NATIVE=1 to force the Python path.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading
from typing import Optional

_lock = threading.Lock()
_mod = None
_tried = False
# diagnosis for "toolchain present but build/load failed": tests fail
# loudly on it instead of silently skipping while runtime degrades to
# the 10-20x slower Python encoder
last_build_error: Optional[str] = None


def _build_dir() -> str:
    d = os.environ.get(
        "GATEKEEPER_TPU_NATIVE_DIR",
        os.path.expanduser("~/.cache/gatekeeper_tpu/native"),
    )
    os.makedirs(d, exist_ok=True)
    return d


def load_flatten_native():
    """-> the _flatten_native module, building it if needed; None when
    disabled or the build fails."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if os.environ.get("GATEKEEPER_TPU_NO_NATIVE") == "1":
            return None
        global last_build_error
        try:
            _mod = _load_or_build()
        except subprocess.CalledProcessError as e:
            last_build_error = (e.stderr or b"").decode(
                "utf-8", "replace"
            ) or str(e)
            _mod = None
        except Exception as e:
            last_build_error = repr(e)
            _mod = None
        return _mod


def _load_or_build():
    import hashlib

    src = os.path.join(os.path.dirname(__file__), "flatten.c")
    out_dir = _build_dir()
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    # content-addressed artifact: any source edit rebuilds
    with open(src, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(out_dir, f"_flatten_native_{tag}{suffix}")
    if not os.path.exists(so):
        cc = os.environ.get("CC", "gcc")
        include = sysconfig.get_paths()["include"]
        # unique temp name: concurrent builders must not clobber each
        # other mid-write (os.replace makes the install atomic)
        tmp = f"{so}.build.{os.getpid()}"
        cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src,
               "-o", tmp]
        if sys.platform == "darwin":
            # clang needs the Python symbols left undefined at link time
            cmd[2:2] = ["-undefined", "dynamic_lookup"]
        subprocess.run(
            cmd,
            check=True,
            capture_output=True,
        )
        os.replace(tmp, so)
    spec = importlib.util.spec_from_file_location("_flatten_native", so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
