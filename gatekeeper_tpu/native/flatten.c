/* Native JSON->token flattener: the C counterpart of
 * gatekeeper_tpu/flatten/encoder.flatten_leaves + the vid/vnum logic of
 * encode_token_table.
 *
 * This is the host-side "JSON -> tensor flattening" native component
 * SURVEY §2 reserves for C++ (the reference has no native code at all —
 * its hot loop is Go; ours is the encode of 100k+ objects per corpus
 * change, which in pure Python costs tens of seconds).
 *
 * Design: walk the already-parsed Python object tree with the CPython
 * API and intern directly into the caller's Vocab dict/list — one
 * source of truth, no side hash table to keep consistent. Semantics are
 * replicated exactly from encoder.py/vocab.py:
 *   - esc_seg: '%' -> %25, '.' -> %2E, a lone "#" key -> %23
 *   - dict insertion order preserved (PyDict_Next); bool checked before
 *     int (Python bool is an int subtype)
 *   - array index lifting: first two levels -> idx0/idx1, deeper
 *     levels saturate
 *   - K_STR vnum = k8s quantity parse (resource.ParseQuantity subset,
 *     vocab._QUANTITY_RE); K_NUM vnum = float(v); K_BOOL 1/0
 *   - val_id normalization: integral floats intern as ints; numbers as
 *     "j:" + json.dumps(v); bool "j:true"/"j:false"; null "j:null"
 * Differential parity with the Python encoder is pinned by
 * tests/test_native_flatten.py.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#include <stdlib.h>
#include <math.h>

/* token value kinds (encoder.py) */
#define K_NULL 0
#define K_BOOL 1
#define K_NUM 2
#define K_STR 3
#define K_EMPTY_OBJ 4
#define K_EMPTY_ARR 5

typedef struct {
    int32_t *spath, *idx0, *idx1, *kind, *vid;
    float *vnum;
    Py_ssize_t len, cap;
    int32_t *row_off; /* [n_rows+1] offsets into the flat arrays */
    Py_ssize_t rows_len, rows_cap;
    int depth;        /* recursion guard (C stack overflow would
                         segfault where Python raises RecursionError) */
    char *path;       /* growing "a.b.#.c" buffer */
    Py_ssize_t path_len, path_cap;
    PyObject *ids;    /* intern-target _ids dict (borrowed) */
    PyObject *strs;   /* intern-target _strs list (borrowed) */
    PyObject *quant;  /* intern-target _quantity list (borrowed) */
    PyObject *base_ids; /* overlay mode: read-only base vocab dict
                           consulted before the local dict (chain
                           lookup, no O(|vocab|) copy per batch —
                           ADVICE r4); NULL for a plain Vocab */
    Py_ssize_t base_len; /* overlay id offset: local ids start here */
    PyObject *py_qty; /* vocab.parse_quantity callable (borrowed) —
                         fallback for inputs the C parser cannot
                         replicate bit-exactly (non-ASCII whitespace,
                         very long mantissas) */
} Enc;

static int enc_grow(Enc *e) {
    Py_ssize_t cap = e->cap ? e->cap * 2 : 4096;
    void *p;
#define GROW(f, t) p = realloc(e->f, cap * sizeof(t)); if (!p) return -1; e->f = (t *)p;
    GROW(spath, int32_t) GROW(idx0, int32_t) GROW(idx1, int32_t)
    GROW(kind, int32_t) GROW(vid, int32_t) GROW(vnum, float)
#undef GROW
    e->cap = cap;
    return 0;
}

static int path_reserve(Enc *e, Py_ssize_t extra) {
    if (e->path_len + extra + 1 <= e->path_cap) return 0;
    Py_ssize_t cap = e->path_cap ? e->path_cap : 256;
    while (cap < e->path_len + extra + 1) cap *= 2;
    char *p = realloc(e->path, cap);
    if (!p) return -1;
    e->path = p;
    e->path_cap = cap;
    return 0;
}

/* k8s quantity parse mirroring vocab._QUANTITY_RE + _SUFFIX; -> 1 when
 * s parses (sets *out), 0 when it doesn't, -1 when the C parser cannot
 * decide bit-exactly (caller falls back to the Python parser):
 * non-ASCII bytes (str.strip() is Unicode-aware) or mantissas past the
 * fixed buffer. */
static int parse_quantity(const char *s, Py_ssize_t n, double *out) {
    for (Py_ssize_t j = 0; j < n; j++)
        if ((unsigned char)s[j] >= 0x80) return -1;
    /* python str.strip() whitespace (ASCII subset; >=0x80 fell back
     * above): space, \t-\r, and \x1c-\x1f */
#define IS_WS(c) ((c) == ' ' || ((c) >= '\t' && (c) <= '\r') \
                  || ((c) >= 0x1c && (c) <= 0x1f))
    while (n && IS_WS((unsigned char)s[0])) { s++; n--; }
    while (n && IS_WS((unsigned char)s[n-1])) n--;
#undef IS_WS
    if (!n) return 0;
    Py_ssize_t i = 0;
    if (s[i] == '+' || s[i] == '-') i++;
    Py_ssize_t dstart = i;
    while (i < n && s[i] >= '0' && s[i] <= '9') i++;
    if (i == dstart) return 0; /* at least one digit required */
    if (i < n && s[i] == '.') {
        i++;
        Py_ssize_t f = i;
        while (i < n && s[i] >= '0' && s[i] <= '9') i++;
        if (i == f) return 0; /* "1." not allowed by the regex */
    }
    if (i < n && (s[i] == 'e' || s[i] == 'E')) {
        Py_ssize_t esave = i;
        i++;
        if (i < n && (s[i] == '+' || s[i] == '-')) i++;
        Py_ssize_t d = i;
        while (i < n && s[i] >= '0' && s[i] <= '9') i++;
        if (i == d) { i = esave; } /* bare "e" is part of the suffix? no:
            regex requires digits after e; backtrack to treat as suffix
            (which will then fail unless it matches a unit) */
    }
    double mult = 1.0;
    Py_ssize_t rem = n - i;
    const char *suf = s + i;
    if (rem == 0) mult = 1.0;
    else if (rem == 1) {
        switch (suf[0]) {
            case 'm': mult = 1e-3; break;
            case 'k': mult = 1e3; break;
            case 'M': mult = 1e6; break;
            case 'G': mult = 1e9; break;
            case 'T': mult = 1e12; break;
            case 'P': mult = 1e15; break;
            case 'E': mult = 1e18; break;
            default: return 0;
        }
    } else if (rem == 2 && suf[1] == 'i') {
        switch (suf[0]) {
            case 'K': mult = 1024.0; break;
            case 'M': mult = 1048576.0; break;
            case 'G': mult = 1073741824.0; break;
            case 'T': mult = 1099511627776.0; break;
            case 'P': mult = 1125899906842624.0; break;
            case 'E': mult = 1152921504606846976.0; break;
            default: return 0;
        }
    } else return 0;
    char buf[64];
    if (i >= (Py_ssize_t)sizeof(buf)) return -1; /* python fallback */
    memcpy(buf, s, i);
    buf[i] = 0;
    char *end = NULL;
    double v = PyOS_string_to_double(buf, &end, NULL);
    if (end == NULL || *end != 0) { PyErr_Clear(); return 0; }
    *out = v * mult;
    return 1;
}

/* parse_quantity with the Python fallback for undecidable inputs;
 * -> 1 parsed (sets *out), 0 not a quantity, -1 python error. */
static int quantity_full(Enc *e, const char *s, Py_ssize_t n, double *out) {
    int rc = parse_quantity(s, n, out);
    if (rc >= 0) return rc;
    PyObject *arg = PyUnicode_DecodeUTF8(s, n, NULL);
    if (!arg) return -1;
    PyObject *res = PyObject_CallFunctionObjArgs(e->py_qty, arg, NULL);
    Py_DECREF(arg);
    if (!res) return -1;
    if (res == Py_None) { Py_DECREF(res); return 0; }
    double v = PyFloat_AsDouble(res);
    Py_DECREF(res);
    if (v == -1.0 && PyErr_Occurred()) return -1;
    *out = v;
    return 1;
}

/* vocab.intern("..."): dict lookup, else append (computing the quantity
 * memo like Vocab.intern does). Overlay mode consults the base dict
 * first (entries below the base_len snapshot only) and assigns local
 * ids from base_len up. Returns id or -1 on error. */
static int32_t intern(Enc *e, PyObject *key) {
    if (e->base_ids) {
        PyObject *bhit = PyDict_GetItemWithError(e->base_ids, key);
        if (bhit) {
            long v = PyLong_AsLong(bhit);
            if (v >= 0 && v < e->base_len) return (int32_t)v;
        } else if (PyErr_Occurred()) {
            return -1;
        }
    }
    PyObject *hit = PyDict_GetItemWithError(e->ids, key);
    if (hit) return (int32_t)PyLong_AsLong(hit);
    if (PyErr_Occurred()) return -1;
    Py_ssize_t id = e->base_len + PyList_GET_SIZE(e->strs);
    PyObject *idobj = PyLong_FromSsize_t(id);
    if (!idobj) return -1;
    if (PyDict_SetItem(e->ids, key, idobj) < 0) { Py_DECREF(idobj); return -1; }
    Py_DECREF(idobj);
    if (PyList_Append(e->strs, key) < 0) return -1;
    /* Vocab.intern also appends parse_quantity(s) to _quantity */
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(key, &n);
    if (!s) return -1;
    double q;
    PyObject *qobj;
    int qrc = quantity_full(e, s, n, &q);
    if (qrc < 0) return -1;
    if (qrc) qobj = PyFloat_FromDouble(q);
    else { qobj = Py_None; Py_INCREF(Py_None); }
    if (!qobj) return -1;
    int rc = PyList_Append(e->quant, qobj);
    Py_DECREF(qobj);
    if (rc < 0) return -1;
    return (int32_t)id;
}

static int32_t intern_prefixed(Enc *e, const char *prefix,
                               const char *s, Py_ssize_t n) {
    Py_ssize_t pl = (Py_ssize_t)strlen(prefix);
    char stack[512];
    char *buf = (pl + n + 1 <= (Py_ssize_t)sizeof(stack))
        ? stack : malloc(pl + n + 1);
    if (!buf) return -1;
    memcpy(buf, prefix, pl);
    memcpy(buf + pl, s, n);
    buf[pl + n] = 0;
    PyObject *k = PyUnicode_DecodeUTF8(buf, pl + n, NULL);
    if (buf != stack) free(buf);
    if (!k) return -1;
    int32_t id = intern(e, k);
    Py_DECREF(k);
    return id;
}

/* Emit one token: the PATH interns before the VALUE (id-assignment
 * order must match the Python encoder exactly — ids are load-bearing).
 * vpre == NULL -> vid -1 (empty obj/arr tokens). */
static int emit(Enc *e, int32_t i0, int32_t i1, int32_t kind,
                const char *vpre, const char *vs, Py_ssize_t vn,
                float vnum) {
    if (e->len >= e->cap && enc_grow(e) < 0) { PyErr_NoMemory(); return -1; }
    int32_t pid = intern_prefixed(e, "p:", e->path, e->path_len);
    if (pid < 0 && PyErr_Occurred()) return -1;
    int32_t vid = -1;
    if (vpre) {
        vid = intern_prefixed(e, vpre, vs, vn);
        if (vid < 0 && PyErr_Occurred()) return -1;
    }
    e->spath[e->len] = pid;
    e->idx0[e->len] = i0;
    e->idx1[e->len] = i1;
    e->kind[e->len] = kind;
    e->vid[e->len] = vid;
    e->vnum[e->len] = vnum;
    e->len++;
    return 0;
}

/* esc_seg: append the escaped key to the path buffer */
static int push_seg(Enc *e, PyObject *key, Py_ssize_t *save_len) {
    *save_len = e->path_len;
    PyObject *kstr = key;
    PyObject *tmp = NULL;
    if (!PyUnicode_Check(key)) {
        tmp = PyObject_Str(key);
        if (!tmp) return -1;
        kstr = tmp;
    }
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(kstr, &n);
    if (!s) { Py_XDECREF(tmp); return -1; }
    int needs = 0;
    for (Py_ssize_t i = 0; i < n; i++)
        if (s[i] == '%' || s[i] == '.') { needs = 1; break; }
    int lone_hash = (n == 1 && s[0] == '#');
    if (path_reserve(e, n * 3 + 2) < 0) { Py_XDECREF(tmp); PyErr_NoMemory(); return -1; }
    if (e->path_len) e->path[e->path_len++] = '.';
    if (lone_hash) {
        memcpy(e->path + e->path_len, "%23", 3);
        e->path_len += 3;
    } else if (needs) {
        for (Py_ssize_t i = 0; i < n; i++) {
            if (s[i] == '%') { memcpy(e->path + e->path_len, "%25", 3); e->path_len += 3; }
            else if (s[i] == '.') { memcpy(e->path + e->path_len, "%2E", 3); e->path_len += 3; }
            else e->path[e->path_len++] = s[i];
        }
    } else {
        memcpy(e->path + e->path_len, s, n);
        e->path_len += n;
    }
    Py_XDECREF(tmp);
    return 0;
}

static int rec(Enc *e, PyObject *v, int32_t i0, int32_t i1);

static int rec_dict(Enc *e, PyObject *v, int32_t i0, int32_t i1) {
    if (PyDict_GET_SIZE(v) == 0)
        return emit(e, i0, i1, K_EMPTY_OBJ, NULL, NULL, 0, 0.0f);
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
        Py_ssize_t save;
        if (push_seg(e, key, &save) < 0) return -1;
        if (rec(e, val, i0, i1) < 0) return -1;
        e->path_len = save;
    }
    return 0;
}

static int rec_list(Enc *e, PyObject *v, int32_t i0, int32_t i1) {
    Py_ssize_t n = PyList_GET_SIZE(v);
    if (n == 0)
        return emit(e, i0, i1, K_EMPTY_ARR, NULL, NULL, 0, 0.0f);
    Py_ssize_t save = e->path_len;
    if (path_reserve(e, 2) < 0) { PyErr_NoMemory(); return -1; }
    if (e->path_len) e->path[e->path_len++] = '.';
    e->path[e->path_len++] = '#';
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t n0 = i0, n1 = i1;
        if (i0 < 0) n0 = (int32_t)i;
        else if (i1 < 0) n1 = (int32_t)i;
        /* >2 array levels: indices saturate */
        if (rec(e, PyList_GET_ITEM(v, i), n0, n1) < 0) return -1;
    }
    e->path_len = save;
    return 0;
}

#define MAX_DEPTH 512

static int rec(Enc *e, PyObject *v, int32_t i0, int32_t i1) {
    if (PyDict_Check(v) || PyList_Check(v)) {
        if (++e->depth > MAX_DEPTH) {
            e->depth--;
            PyErr_SetString(PyExc_RecursionError,
                            "object nesting too deep for native flatten");
            return -1;
        }
        int rc = PyDict_Check(v) ? rec_dict(e, v, i0, i1)
                                 : rec_list(e, v, i0, i1);
        e->depth--;
        return rc;
    }
    if (PyBool_Check(v)) {
        int truth = (v == Py_True);
        return emit(e, i0, i1, K_BOOL, "j:", truth ? "true" : "false",
                    truth ? 4 : 5, truth ? 1.0f : 0.0f);
    }
    if (PyLong_Check(v)) {
        double d = PyLong_AsDouble(v);
        if (d == -1.0 && PyErr_Occurred()) return -1;
        PyObject *s = PyObject_Str(v);
        if (!s) return -1;
        Py_ssize_t n;
        const char *cs = PyUnicode_AsUTF8AndSize(s, &n);
        if (!cs) { Py_DECREF(s); return -1; }
        int rc = emit(e, i0, i1, K_NUM, "j:", cs, n, (float)d);
        Py_DECREF(s);
        return rc;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        char *repr;
        PyObject *s = NULL;
        /* val_id: integral floats normalize to ints */
        if (isfinite(d) && d == floor(d)) {
            PyObject *asint = PyLong_FromDouble(d);
            if (!asint) return -1;
            s = PyObject_Str(asint);
            Py_DECREF(asint);
        } else if (isnan(d)) {
            s = PyUnicode_FromString("NaN");        /* json.dumps */
        } else if (isinf(d)) {
            s = PyUnicode_FromString(d > 0 ? "Infinity" : "-Infinity");
        } else {
            repr = PyOS_double_to_string(d, 'r', 0, 0, NULL);
            if (!repr) return -1;
            s = PyUnicode_FromString(repr);
            PyMem_Free(repr);
        }
        if (!s) return -1;
        Py_ssize_t n;
        const char *cs = PyUnicode_AsUTF8AndSize(s, &n);
        if (!cs) { Py_DECREF(s); return -1; }
        int rc = emit(e, i0, i1, K_NUM, "j:", cs, n, (float)d);
        Py_DECREF(s);
        return rc;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char *cs = PyUnicode_AsUTF8AndSize(v, &n);
        if (!cs) return -1;
        /* K_STR vnum: quantity parse (encode_token_table) */
        double q;
        int qrc = quantity_full(e, cs, n, &q);
        if (qrc < 0) return -1;
        float vnum = qrc ? (float)q : 0.0f;
        return emit(e, i0, i1, K_STR, "s:", cs, n, vnum);
    }
    if (v == Py_None)
        return emit(e, i0, i1, K_NULL, "j:", "null", 4, 0.0f);
    /* non-JSON scalar (shouldn't happen for K8s objects): skip like the
     * Python generator (no branch matches -> nothing yielded) */
    return 0;
}

static PyObject *encode_rows(PyObject *self, PyObject *args) {
    PyObject *objs, *ids, *strs, *quant, *py_qty;
    PyObject *base_ids = Py_None;
    Py_ssize_t base_len = 0;
    if (!PyArg_ParseTuple(args, "OOOOO|On", &objs, &ids, &strs, &quant,
                          &py_qty, &base_ids, &base_len))
        return NULL;
    if (!PyList_Check(objs) || !PyDict_Check(ids) || !PyList_Check(strs)
        || !PyList_Check(quant) || !PyCallable_Check(py_qty)
        || (base_ids != Py_None && !PyDict_Check(base_ids))) {
        PyErr_SetString(
            PyExc_TypeError,
            "encode_rows(list, dict, list, list, parse_quantity"
            "[, base_ids_dict, base_len])");
        return NULL;
    }
    Enc e;
    memset(&e, 0, sizeof(e));
    e.ids = ids; e.strs = strs; e.quant = quant; e.py_qty = py_qty;
    e.base_ids = (base_ids == Py_None) ? NULL : base_ids;
    e.base_len = base_len;
    Py_ssize_t n_rows = PyList_GET_SIZE(objs);
    e.row_off = malloc((n_rows + 1) * sizeof(int32_t));
    if (!e.row_off || path_reserve(&e, 64) < 0 || enc_grow(&e) < 0) {
        PyErr_NoMemory();
        goto fail;
    }
    for (Py_ssize_t r = 0; r < n_rows; r++) {
        e.row_off[r] = (int32_t)e.len;
        e.path_len = 0;
        if (rec(&e, PyList_GET_ITEM(objs, r), -1, -1) < 0) goto fail;
    }
    e.row_off[n_rows] = (int32_t)e.len;

    PyObject *out = Py_BuildValue(
        "(y#y#y#y#y#y#y#)",
        (char *)e.spath, e.len * sizeof(int32_t),
        (char *)e.idx0, e.len * sizeof(int32_t),
        (char *)e.idx1, e.len * sizeof(int32_t),
        (char *)e.kind, e.len * sizeof(int32_t),
        (char *)e.vid, e.len * sizeof(int32_t),
        (char *)e.vnum, e.len * sizeof(float),
        (char *)e.row_off, (n_rows + 1) * sizeof(int32_t));
    free(e.spath); free(e.idx0); free(e.idx1); free(e.kind);
    free(e.vid); free(e.vnum); free(e.row_off); free(e.path);
    return out;
fail:
    free(e.spath); free(e.idx0); free(e.idx1); free(e.kind);
    free(e.vid); free(e.vnum); free(e.row_off); free(e.path);
    return NULL;
}

static PyMethodDef methods[] = {
    {"encode_rows", encode_rows, METH_VARARGS,
     "encode_rows(objs, vocab_ids, vocab_strs, vocab_quantity, parse_quantity) -> "
     "(spath, idx0, idx1, kind, vid, vnum, row_offsets) raw buffers"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_flatten_native", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__flatten_native(void) {
    return PyModule_Create(&module);
}
