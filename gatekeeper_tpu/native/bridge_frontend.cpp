// Admission serving bridge, native front half.
//
// The reference's webhook is a Go HTTP server (goroutine per request,
// pkg/webhook/policy.go:141); SURVEY §2.4 row 3 / §7 step 5 reserve a
// native front for this framework: a C++ process that terminates the
// admission HTTP traffic on a thread pool (no Python GIL on the accept
// path) and streams each AdmissionReview body over a Unix socket to the
// Python/JAX batch server (webhook/bridge.py), which micro-batches into
// the fused device dispatch.
//
// Protocol (frontend <-> backend): length-prefixed frames over one Unix
// socket per in-flight request — [u32 big-endian length][payload]. The
// request payload is "<http path>\n<raw AdmissionReview JSON body>"
// (the backend routes /v1/admit vs /v1/admitlabel on the first line);
// the response payload is the complete AdmissionReview response JSON.
//
// Failure semantics mirror the reference's fail-open posture
// (failurePolicy: Ignore, policy.go:80): a backend that is down or
// misses --deadline-ms gets an allow-with-warning response so admission
// never wedges the cluster; the audit sweep remains the backstop.
//
// Build: g++ -O2 -pthread -o bridge_frontend bridge_frontend.cpp
// Run:   bridge_frontend --port 0 --backend /tmp/gk.sock \
//          [--deadline-ms 2000] [--threads 64]
// Prints "LISTENING <port>" on stdout once bound.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Config {
  int port = 0;
  std::string backend;
  int deadline_ms = 2000;
  int threads = 64;  // accept backlog workers (thread per connection)
};

std::atomic<bool> g_stop{false};

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// blocking write toward the HTTP client (the apiserver side has its own
// webhook timeout; our --deadline-ms governs only the backend hop)
bool write_full(int fd, const void* buf, size_t n) {
  size_t sent = 0;
  const char* p = static_cast<const char*>(buf);
  while (sent < n) {
    ssize_t w = write(fd, p + sent, n - sent);
    if (w <= 0) return false;
    sent += static_cast<size_t>(w);
  }
  return true;
}

// `deadline` is an absolute CLOCK_MONOTONIC ms instant: the whole
// backend round trip shares ONE budget (per-poll timeouts would let a
// trickling or stalled peer stretch it arbitrarily).
ssize_t read_deadline(int fd, void* buf, size_t n, int64_t deadline) {
  size_t got = 0;
  auto* p = static_cast<char*>(buf);
  while (got < n) {
    int remain = static_cast<int>(deadline - now_ms());
    if (remain <= 0) return -1;
    struct pollfd pfd{fd, POLLIN, 0};
    int pr = poll(&pfd, 1, remain);
    if (pr <= 0) return -1;  // timeout or error
    ssize_t r = read(fd, p + got, n - got);
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return static_cast<ssize_t>(got);
}

bool write_deadline(int fd, const void* buf, size_t n, int64_t deadline) {
  size_t sent = 0;
  const char* p = static_cast<const char*>(buf);
  while (sent < n) {
    int remain = static_cast<int>(deadline - now_ms());
    if (remain <= 0) return false;
    struct pollfd pfd{fd, POLLOUT, 0};
    int pr = poll(&pfd, 1, remain);
    if (pr <= 0) return false;
    ssize_t w = write(fd, p + sent, n - sent);
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) continue;
    if (w <= 0) return false;
    sent += static_cast<size_t>(w);
  }
  return true;
}

bool connect_deadline(int fd, const struct sockaddr* addr, socklen_t alen,
                      int64_t deadline) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = connect(fd, addr, alen);
  if (rc != 0 && errno != EINPROGRESS) return false;
  if (rc != 0) {
    int remain = static_cast<int>(deadline - now_ms());
    if (remain <= 0) return false;
    struct pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, remain) <= 0) return false;
    int err = 0;
    socklen_t elen = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 || err != 0)
      return false;
  }
  return true;  // socket stays non-blocking; read/write poll anyway
}

// One round trip to the Python batch server; empty string = failure.
// The frame payload is "<path>\n<body>" so the backend can route.
std::string backend_call(const Config& cfg, const std::string& path,
                         const std::string& body) {
  int64_t deadline = now_ms() + cfg.deadline_ms;
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return "";
  struct sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, cfg.backend.c_str(),
               sizeof(addr.sun_path) - 1);
  if (!connect_deadline(fd, reinterpret_cast<struct sockaddr*>(&addr),
                        sizeof(addr), deadline)) {
    close(fd);
    return "";
  }
  std::string payload = path + "\n" + body;
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  if (!write_deadline(fd, &len, 4, deadline) ||
      !write_deadline(fd, payload.data(), payload.size(), deadline)) {
    close(fd);
    return "";
  }
  uint32_t rlen_be = 0;
  if (read_deadline(fd, &rlen_be, 4, deadline) != 4) {
    close(fd);
    return "";
  }
  uint32_t rlen = ntohl(rlen_be);
  if (rlen > (64u << 20)) {  // 64MB sanity cap
    close(fd);
    return "";
  }
  std::string out(rlen, '\0');
  if (read_deadline(fd, out.data(), rlen, deadline) !=
      static_cast<ssize_t>(rlen)) {
    close(fd);
    return "";
  }
  close(fd);
  return out;
}

// Top-level "uid" of the AdmissionReview's request object, or "".
// Tracks brace depth and string state so a uid nested deeper (e.g.
// request.object.metadata.uid serialized first) can never shadow the
// request's own uid (ADVICE r4: a naive first-"uid" scan returns the
// wrong uid under reordered keys, and the apiserver rejects the
// response).
std::string extract_request_uid(const std::string& body) {
  size_t req = body.find("\"request\"");
  if (req == std::string::npos) return "";
  size_t i = body.find('{', req);
  if (i == std::string::npos) return "";
  int depth = 0;
  while (i < body.size()) {
    char c = body[i];
    if (c == '"') {
      size_t start = ++i;
      while (i < body.size() && body[i] != '"') {
        i += (body[i] == '\\') ? 2 : 1;
      }
      if (i >= body.size()) return "";
      std::string s = body.substr(start, i - start);
      ++i;  // past closing quote
      if (depth == 1) {
        size_t j = body.find_first_not_of(" \t\r\n", i);
        if (j != std::string::npos && body[j] == ':' && s == "uid") {
          size_t k = body.find_first_not_of(" \t\r\n", j + 1);
          if (k == std::string::npos || body[k] != '"') return "";
          size_t vstart = ++k;
          while (k < body.size() && body[k] != '"') {
            k += (body[k] == '\\') ? 2 : 1;
          }
          if (k >= body.size()) return "";
          return body.substr(vstart, k - vstart);
        }
      }
      continue;
    }
    if (c == '{' || c == '[') {
      ++depth;
    } else if (c == '}' || c == ']') {
      if (--depth <= 0) return "";  // left the request object: no uid
    }
    ++i;
  }
  return "";
}

// Fail-open AdmissionReview response (uid copied from the request when
// findable; the apiserver tolerates an empty uid on failurePolicy
// retries, but we extract it for correctness).
std::string fail_open_response(const std::string& body) {
  std::string uid = extract_request_uid(body);
  std::string resp =
      "{\"apiVersion\":\"admission.k8s.io/v1\",\"kind\":\"AdmissionReview\","
      "\"response\":{\"uid\":\"" + uid + "\",\"allowed\":true,"
      "\"warnings\":[\"gatekeeper-tpu backend unavailable or over "
      "deadline; failing open (audit is the backstop)\"]}}";
  return resp;
}

void respond(int fd, int code, const std::string& reason,
             const std::string& body, bool keep_alive) {
  std::string head = "HTTP/1.1 " + std::to_string(code) + " " + reason +
                     "\r\nContent-Type: application/json\r\n"
                     "Content-Length: " + std::to_string(body.size()) +
                     "\r\nConnection: " +
                     (keep_alive ? "keep-alive" : "close") + "\r\n\r\n";
  write_full(fd, head.data(), head.size());
  write_full(fd, body.data(), body.size());
}

// Reads one HTTP request; returns false to close the connection.
// `carry` holds bytes read past the previous request's body on this
// keep-alive connection (pipelined requests); leftovers from THIS
// request are stored back into it (ADVICE r4: truncating them broke
// pipelining).
bool handle_one(const Config& cfg, int fd, std::string& carry) {
  // read until end of headers (the carry may already hold a request)
  std::string buf = std::move(carry);
  carry.clear();
  char tmp[4096];
  size_t header_end = buf.find("\r\n\r\n");
  while (header_end == std::string::npos) {
    struct pollfd pfd{fd, POLLIN, 0};
    // generous idle keep-alive window
    int pr = poll(&pfd, 1, 30000);
    if (pr <= 0) return false;
    ssize_t r = read(fd, tmp, sizeof(tmp));
    if (r <= 0) return false;
    buf.append(tmp, static_cast<size_t>(r));
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > (1u << 20) && header_end == std::string::npos)
      return false;  // oversized headers
  }
  std::string headers = buf.substr(0, header_end);
  std::string body = buf.substr(header_end + 4);

  // request line
  size_t sp1 = headers.find(' ');
  size_t sp2 = headers.find(' ', sp1 + 1);
  if (sp1 == std::string::npos || sp2 == std::string::npos) return false;
  std::string method = headers.substr(0, sp1);
  std::string path = headers.substr(sp1 + 1, sp2 - sp1 - 1);

  // content-length (case-insensitive scan); chunked framing is not
  // implemented — reject it explicitly rather than misparse
  size_t content_length = 0;
  {
    std::string lower = headers;
    for (auto& ch : lower) ch = static_cast<char>(tolower(ch));
    if (lower.find("transfer-encoding:") != std::string::npos) {
      respond(fd, 501, "Not Implemented",
              "{\"error\":\"chunked transfer encoding not supported\"}",
              false);
      return false;
    }
    size_t cl = lower.find("content-length:");
    if (cl != std::string::npos)
      content_length = std::strtoul(lower.c_str() + cl + 15, nullptr, 10);
    if (content_length > (64u << 20)) return false;
  }
  while (body.size() < content_length) {
    struct pollfd pfd{fd, POLLIN, 0};
    int pr = poll(&pfd, 1, 10000);
    if (pr <= 0) return false;
    ssize_t r = read(fd, tmp, sizeof(tmp));
    if (r <= 0) return false;
    body.append(tmp, static_cast<size_t>(r));
  }
  if (body.size() > content_length) {
    carry = body.substr(content_length);  // next pipelined request
    body.resize(content_length);
  }

  if (path == "/healthz") {
    respond(fd, 200, "OK", "{\"ok\":true}", true);
    return true;
  }
  if (method != "POST" ||
      (path != "/v1/admit" && path != "/v1/admitlabel")) {
    respond(fd, 404, "Not Found", "{\"error\":\"not found\"}", true);
    return true;
  }
  std::string out = backend_call(cfg, path, body);
  if (out.empty()) out = fail_open_response(body);
  respond(fd, 200, "OK", out, true);
  return true;
}

std::atomic<int> g_conns{0};

void serve_conn(const Config& cfg, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string carry;
  while (!g_stop.load() && handle_one(cfg, fd, carry)) {
  }
  close(fd);
  g_conns.fetch_sub(1);
}

}  // namespace

int main(int argc, char** argv) {
  Config cfg;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&](int& i) -> const char* {
      return (i + 1 < argc) ? argv[++i] : "";
    };
    if (a == "--port") cfg.port = std::atoi(next(i));
    else if (a == "--backend") cfg.backend = next(i);
    else if (a == "--deadline-ms") cfg.deadline_ms = std::atoi(next(i));
    else if (a == "--threads") cfg.threads = std::atoi(next(i));
  }
  if (cfg.backend.empty()) {
    std::fprintf(stderr, "--backend <unix socket path> is required\n");
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(cfg.port));
  if (bind(lfd, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0 ||
      listen(lfd, 1024) != 0) {
    std::perror("bind/listen");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  while (!g_stop.load()) {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // thread per keep-alive connection: the apiserver maintains a
    // modest pool of long-lived connections, far below thread limits.
    // Cap concurrency (4x --threads) so a connection flood degrades to
    // 503s instead of unbounded threads x 64MB body buffers (ADVICE r4)
    if (g_conns.load() >= cfg.threads * 4) {
      respond(cfd, 503, "Service Unavailable",
              "{\"error\":\"connection limit reached\"}", false);
      // drain briefly before close: unread request bytes trigger an
      // RST that can discard the queued 503 (the client would see
      // ECONNRESET, not the degraded-but-clean rejection). The bounded
      // drain also backpressures the accept loop under a flood.
      shutdown(cfd, SHUT_WR);
      char sink[4096];
      int64_t drain_deadline = now_ms() + 100;
      for (;;) {
        int remain = static_cast<int>(drain_deadline - now_ms());
        if (remain <= 0) break;
        struct pollfd pfd{cfd, POLLIN, 0};
        if (poll(&pfd, 1, remain) <= 0) break;
        if (read(cfd, sink, sizeof(sink)) <= 0) break;
      }
      close(cfd);
      continue;
    }
    g_conns.fetch_add(1);
    std::thread(serve_conn, std::cref(cfg), cfd).detach();
  }
  close(lfd);
  return 0;
}
