"""Admission-webhook replay bench: BASELINE config #4.

Mirrors the reference harness BenchmarkValidationHandler
(pkg/webhook/policy_benchmark_test.go:233-329): PSP-style constraint
load, synthesized UPDATE AdmissionRequests, handler-level measurement
(the Go benchmark calls Handle directly too — no HTTP client in the
loop). Replays N requests at several concurrencies through the
micro-batching handler and reports p50/p99 latency, throughput, and
batch occupancy.

Standalone: python bench_webhook.py [N_REQUESTS] [N_CONSTRAINTS]
Also importable by bench.py (run_webhook_bench).
"""

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

TARGET = "admission.k8s.gatekeeper.sh"
LIB = "/root/reference/library"

WEBHOOK_MIX = [
    (f"{LIB}/pod-security-policy/privileged-containers",
     "K8sPSPPrivilegedContainer", None),
    (f"{LIB}/pod-security-policy/host-namespaces",
     "K8sPSPHostNamespace", None),
    (f"{LIB}/pod-security-policy/capabilities", "K8sPSPCapabilities",
     {"allowedCapabilities": ["CHOWN"], "requiredDropCapabilities": []}),
    (f"{LIB}/general/allowedrepos", "K8sAllowedRepos",
     {"repos": ["nginx", "gcr.io/prod"]}),
    (f"{LIB}/general/requiredlabels", "K8sRequiredLabels",
     {"labels": [{"key": "app"}]}),
]


# repo-local fallback mix for containers without the reference
# checkout: the shipped reference-library bundle, same constraint
# shape and the same 100%-violating stress coverage (privileged +
# repos + labels all trip on make_request's violating pod)
LOCAL_BUNDLE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "deploy", "policies", "reference-library.yaml",
)
LOCAL_MIX = [
    ("K8sPSPPrivileged", None),
    ("K8sAllowedRepos", {"repos": ["nginx", "gcr.io/prod"]}),
    ("K8sRequiredLabels", {"labels": [{"key": "app"}]}),
    ("K8sBlockNodePort", None),
]


def _load_template(path):
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def _webhook_mix():
    """[(template_doc, kind, params)] — the reference checkout's mix
    when present, else the shipped reference-library bundle."""
    if os.path.isdir(LIB):
        return [
            (_load_template(f"{tdir}/template.yaml"), kind, params)
            for tdir, kind, params in WEBHOOK_MIX
        ]
    import yaml

    with open(LOCAL_BUNDLE) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    by_kind = {d["spec"]["crd"]["spec"]["names"]["kind"]: d for d in docs}
    return [
        (by_kind[kind], kind, params)
        for kind, params in LOCAL_MIX
        if kind in by_kind
    ]


def build_webhook_client(driver, n_constraints):
    from gatekeeper_tpu.constraint import Backend, K8sValidationTarget

    mix = _webhook_mix()
    client = Backend(driver).new_client(K8sValidationTarget())
    for doc, _kind, _params in mix:
        client.add_template(doc)
    for i in range(n_constraints):
        _doc, kind, params = mix[i % len(mix)]
        # namespace affinity aligned with make_request's ns{i % 11}: a
        # constraint governs one namespace, so the locality planner can
        # co-locate each namespace's constraints and mask-gated pruned
        # dispatch pays only the partitions a batch's namespaces touch
        # (the reference's per-team constraint scoping, at bench scale)
        spec = {"match": {
            "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
            "namespaces": [f"ns{i % 11}"],
        }}
        if params is not None:
            spec["parameters"] = params
        client.add_constraint(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind,
                "metadata": {"name": f"w{i}"},
                "spec": spec,
            }
        )
    return client


def make_request(i, violating=True):
    """UPDATE AdmissionRequest like the reference's benchmark generator
    (policy_benchmark_test.go:197-231); `violating` pods trip every
    template in the mix (the reference replays 100% violation rate)."""
    sc = {"privileged": True} if violating else {}
    labels = {} if violating else {"app": f"svc{i % 7}"}
    image = "docker.io/evil" if violating else "nginx"
    spec_extra = {"hostPID": True} if violating else {}
    obj = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"req{i}",
            "namespace": f"ns{i % 11}",
            "labels": labels,
        },
        "spec": {
            "containers": [
                {
                    "name": "main",
                    "image": image,
                    "securityContext": sc,
                    **(
                        {}
                        if violating
                        else {"resources": {"limits": {"cpu": "1",
                                                       "memory": "1Gi"}}}
                    ),
                }
            ],
            **spec_extra,
        },
    }
    return {
        "uid": f"uid-{i}",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "operation": "UPDATE",
        "name": obj["metadata"]["name"],
        "namespace": obj["metadata"]["namespace"],
        "userInfo": {"username": "bench"},
        "object": obj,
        "oldObject": obj,
    }


def _warm_route(client):
    """Synchronously compile the fused review route so replays measure
    the compiled path (serve-while-compiling otherwise serves cold
    batches on the interpreter and compiles in the background)."""
    from gatekeeper_tpu.constraint import AugmentedReview

    client.warm_review_path(
        [AugmentedReview(make_request(i)) for i in range(16)]
    )


def replay(handler, requests, concurrency):
    lat = np.zeros(len(requests))

    def one(i):
        t0 = time.perf_counter()
        resp = handler.handle(requests[i])
        lat[i] = time.perf_counter() - t0
        return resp.allowed

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=concurrency) as ex:
        allowed = list(ex.map(one, range(len(requests))))
    wall = time.perf_counter() - t0
    return {
        "concurrency": concurrency,
        "requests": len(requests),
        "wall_seconds": round(wall, 3),
        "throughput_rps": round(len(requests) / wall, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "denied": int(sum(not a for a in allowed)),
    }


def run_webhook_bench(n_requests=10_000, n_constraints=50, err=sys.stderr):
    from gatekeeper_tpu.constraint import RegoDriver, TpuDriver
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    # CPU baseline: serial handler over the interpreter driver (the
    # reference's architecture: one interpreted query per request) on a
    # subsample, scaled
    from gatekeeper_tpu.webhook import ValidationHandler

    cpu_n = min(200, n_requests)
    cpu_client = build_webhook_client(RegoDriver(), n_constraints)
    cpu_handler = ValidationHandler(cpu_client, TARGET)
    cpu_reqs = [make_request(i) for i in range(cpu_n)]
    cpu_handler.handle(cpu_reqs[0])  # warm
    t0 = time.perf_counter()
    for r in cpu_reqs:
        cpu_handler.handle(r)
    cpu_wall = time.perf_counter() - t0
    cpu = {
        "requests": cpu_n,
        "throughput_rps": round(cpu_n / cpu_wall, 1),
        "p50_ms": round(cpu_wall / cpu_n * 1e3, 2),
    }
    print(f"webhook cpu baseline (python interp): {cpu}", file=err)
    # same interp handler under the measured concurrencies, so the
    # fused-vs-interp CROSSOVER is computed like-for-like (VERDICT r4
    # #2: the concurrency where the fused path starts winning)
    interp_by_conc = {}
    n_sub = min(600, n_requests)
    interp_reqs = (cpu_reqs * -(-n_sub // cpu_n))[:n_sub]
    for conc in (8, 128):
        r = replay(cpu_handler, interp_reqs, conc)
        interp_by_conc[conc] = r["throughput_rps"]
        print(
            f"webhook interp concurrent: c={conc} "
            f"rps={r['throughput_rps']} p50={r['p50_ms']}ms",
            file=err,
        )

    from gatekeeper_tpu.obs import Tracer, span_breakdown

    client = build_webhook_client(TpuDriver(), n_constraints)
    # every replayed request is traced; the per-span-name percentile
    # table (span_breakdown) attributes the p99 to its cost center —
    # queue wait vs flatten/encode vs device dispatch vs render
    tracer = Tracer(max_traces=8192)
    batcher = MicroBatcher(client, TARGET, window_ms=2.0, tracer=tracer)
    handler = BatchedValidationHandler(
        batcher, request_timeout=60, tracer=tracer
    )
    batcher.start()
    try:
        # flip the serve-while-compiling route to warm SYNCHRONOUSLY
        # first (a cold device-sized batch otherwise serves on the
        # interpreter and only kicks a background compile), then warm
        # the jit across the occupancy buckets BOTH concurrency
        # profiles produce (batch-size buckets differ between c=8 and
        # c=128; compiles inside the measured replay would skew p99)
        _warm_route(client)
        warm = [make_request(i) for i in range(256)]
        replay(handler, warm, 64)
        replay(handler, [make_request(i) for i in range(512)], 128)
        replay(
            handler,
            [make_request(i, violating=False) for i in range(512)],
            128,
        )
        tracer.clear()  # warmup traces must not pollute the breakdown

        out = []
        # two violation profiles:
        #  * 100% violating — the reference harness's stress shape
        #    (every violating pair renders; since r4 this is the
        #    COMPILED message path, engine/render.py, not per-pair
        #    interpretation);
        #  * 0% violating — the steady-state admission shape where the
        #    fused device screen answers allow without any host render.
        # Lower concurrencies replay subsamples: per-batch round trips
        # over a tunneled chip make full 10k replays take minutes
        # without changing p50.
        for violating in (True, False):
            requests = [
                make_request(i, violating=violating)
                for i in range(n_requests)
            ]
            hi_n = max(1500, n_requests // 6) if violating else (
                max(4000, n_requests // 2)
            )
            for conc, n_sub in ((8, max(400, n_requests // 25)),
                                (128, hi_n)):
                batcher.batches_dispatched = 0
                batcher.requests_batched = 0
                r = replay(handler, requests[:n_sub], conc)
                r["violating"] = violating
                r["batch_occupancy"] = round(
                    batcher.requests_batched
                    / max(1, batcher.batches_dispatched),
                    1,
                )
                out.append(r)
                print(f"webhook replay: {r}", file=err)
        breakdown = span_breakdown(tracer.recent(8192))
        print(f"webhook span breakdown (ms): {breakdown}", file=err)
    finally:
        batcher.stop()
    bridge = run_bridge_bench(n_requests, n_constraints, err=err)
    # explicit crossover: the lowest measured concurrency where the
    # fused device path out-serves the per-request interpreter (below
    # it, MIN_DEVICE_BATCH adaptive routing keeps admission on the
    # interpreter deliberately)
    crossover = None
    for conc in sorted(interp_by_conc):
        fused_rps = next(
            (
                r["throughput_rps"]
                for r in out
                if r["violating"] and r["concurrency"] == conc
            ),
            None,
        )
        if fused_rps is not None and fused_rps > interp_by_conc[conc]:
            crossover = conc
            break
    result = {
        "cpu_python_interp": cpu,
        "interp_rps_by_concurrency": interp_by_conc,
        "fused_vs_interp_crossover_concurrency": crossover,
        "tpu_batched": out,
        # per-span-name p50/p99/max over every measured request: the
        # diagnosable form of the p99 cliff (which cost center blew up)
        "span_breakdown_ms": breakdown,
        "tpu_bridge": bridge,
    }
    print(
        f"fused-vs-interp crossover concurrency: {crossover} "
        f"(interp rps {interp_by_conc})",
        file=err,
    )
    return result


def _mutator_mix(n_mutators):
    """Synthesized mutator load: cycles the three kinds with varied
    match specs so screening exercises the kernel's dimensions."""
    out = []
    for i in range(n_mutators):
        which = i % 3
        if which == 0:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "AssignMetadata",
                "metadata": {"name": f"bm-label-{i}"},
                "spec": {
                    "match": {"scope": "Namespaced"},
                    "location": f"metadata.labels.bench-{i}",
                    "parameters": {"assign": {"value": f"v{i}"}},
                },
            })
        elif which == 1:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "Assign",
                "metadata": {"name": f"bm-assign-{i}"},
                "spec": {
                    "applyTo": [{"groups": [""], "versions": ["v1"],
                                 "kinds": ["Pod"]}],
                    "match": {"kinds": [{"apiGroups": [""],
                                         "kinds": ["Pod"]}],
                              "namespaces": [f"ns{j}" for j in range(11)]},
                    "location": "spec.containers[name: *].imagePullPolicy",
                    "parameters": {"assign": {"value": "Always"}},
                },
            })
        else:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "ModifySet",
                "metadata": {"name": f"bm-set-{i}"},
                "spec": {
                    "applyTo": [{"groups": [""], "versions": ["v1"],
                                 "kinds": ["Pod"]}],
                    "match": {"kinds": [{"apiGroups": [""],
                                         "kinds": ["Pod"]}]},
                    "location": "spec.containers[name: main].args",
                    "parameters": {"operation": "merge",
                                   "values": {"fromList": [f"--flag{i}"]}},
                },
            })
    return out


def run_mutate_bench(n_requests=10_000, n_mutators=30, err=sys.stderr):
    """The mutate-plane replay (`--mutate`): p50/p99/throughput of
    /v1/mutate's handler path at the measured concurrencies, plus the
    per-span breakdown (queue_wait / screen_dispatch / apply_fixpoint /
    render_patch) so the next BENCH round captures the second admission
    plane with the same cost-center attribution as validation."""
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.mutation import MutationSystem
    from gatekeeper_tpu.obs import Tracer, span_breakdown
    from gatekeeper_tpu.webhook.mutate import MutateBatcher, MutationHandler

    metrics = MetricsRegistry()
    tracer = Tracer(max_traces=8192)
    system = MutationSystem(metrics=metrics)
    for m in _mutator_mix(n_mutators):
        system.upsert(m)
    batcher = MutateBatcher(
        system, window_ms=2.0, metrics=metrics, tracer=tracer
    )
    handler = MutationHandler(
        batcher, metrics=metrics, request_timeout=60, tracer=tracer
    )
    batcher.start()
    out = []
    try:
        # warm the screen's jit buckets across both concurrency profiles
        replay(handler, [make_request(i) for i in range(256)], 64)
        replay(handler, [make_request(i) for i in range(512)], 128)
        tracer.clear()
        for conc, n_sub in ((8, max(400, n_requests // 25)),
                            (128, max(1500, n_requests // 6))):
            batcher.batches_dispatched = 0
            batcher.requests_batched = 0
            requests = [make_request(i) for i in range(n_sub)]
            r = replay(handler, requests, conc)
            del r["denied"]  # mutate allows; patch presence is the signal
            r["batch_occupancy"] = round(
                batcher.requests_batched
                / max(1, batcher.batches_dispatched),
                1,
            )
            r["screen_dispatches"] = system.screen_dispatches
            out.append(r)
            print(f"mutate replay: {r}", file=err)
        breakdown = span_breakdown(tracer.recent(8192))
        print(f"mutate span breakdown (ms): {breakdown}", file=err)
    finally:
        batcher.stop()
    snap = metrics.snapshot()
    return {
        "mutators": n_mutators,
        "replays": out,
        "span_breakdown_ms": breakdown,
        "fixpoint_iterations": snap["distributions"].get(
            "mutation_fixpoint_iterations", {}
        ),
        "patch_bytes": snap["distributions"].get("mutation_patch_bytes", {}),
    }


_CHAOS_REGO = """package chaosbench

violation[{"msg": msg}] {
    input.review.object.spec.containers[_].securityContext.privileged
    msg := "privileged container"
}
"""


def build_chaos_client(driver, n_constraints):
    """Self-contained policy load (no reference-library dependency):
    the chaos bench measures the failure ENVELOPE — shed rate, breaker
    behavior, degraded-mode latency — not the policy mix, so one
    inline template with n constraint instances is the right corpus
    and keeps --chaos runnable on any machine."""
    from gatekeeper_tpu.constraint import Backend, K8sValidationTarget

    client = Backend(driver).new_client(K8sValidationTarget())
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "chaosbench"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "ChaosBench"}}},
            "targets": [{"target": TARGET, "rego": _CHAOS_REGO}],
        },
    })
    for i in range(n_constraints):
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "ChaosBench",
            "metadata": {"name": f"cb{i}"},
            "spec": {"match": {"kinds": [
                {"apiGroups": [""], "kinds": ["Pod"]}
            ]}},
        })
    return client


def run_chaos_bench(n_requests=3000, n_constraints=20, err=sys.stderr):
    """The `--chaos` replay (docs/robustness.md): drive the admission
    plane through three phases — clean, device-faulted, recovered — and
    report p50/p99, shed rate, degraded dispatches, and circuit-breaker
    transitions per phase. The faulted phase arms the REAL
    `webhook.batch_dispatch` fault point, so the measured p99 is the
    host-oracle degraded mode the breaker buys (vs paying a doomed
    fused attempt per batch)."""
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.faults import FAULTS, CircuitBreaker
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    metrics = MetricsRegistry()
    client = build_chaos_client(TpuDriver(), n_constraints)
    breaker = CircuitBreaker(
        failure_threshold=3, recovery_seconds=1.0, metrics=metrics
    )
    batcher = MicroBatcher(
        client, TARGET, window_ms=2.0, metrics=metrics,
        max_queue=512, breaker=breaker,
    )
    handler = BatchedValidationHandler(
        batcher, request_timeout=10, metrics=metrics, fail_policy="open"
    )
    n_sub = max(400, n_requests // 6)
    out = []
    batcher.start()
    try:
        _warm_route(client)
        replay(handler, [make_request(i) for i in range(512)], 128)

        def run_phase(name):
            shed0 = batcher.shed_count
            fail0 = batcher.batch_failures
            trans0 = breaker.transitions
            snap0 = metrics.snapshot()["counters"]
            deg_key = 'webhook_degraded_dispatch_total{plane="validation"}'
            deg0 = snap0.get(deg_key, 0)
            r = replay(
                handler, [make_request(i) for i in range(n_sub)], 128
            )
            snap1 = metrics.snapshot()["counters"]
            r.update(
                phase=name,
                shed=batcher.shed_count - shed0,
                shed_rate=round((batcher.shed_count - shed0) / n_sub, 4),
                batch_failures=batcher.batch_failures - fail0,
                degraded_dispatches=snap1.get(deg_key, 0) - deg0,
                breaker_transitions=breaker.transitions - trans0,
                breaker_state=breaker.state,
            )
            out.append(r)
            print(f"chaos phase: {r}", file=err)

        run_phase("clean")
        FAULTS.arm("webhook.batch_dispatch", mode="error")
        run_phase("device_fault")
        FAULTS.reset()
        time.sleep(1.2)  # recovery window: next batch is the probe
        run_phase("recovered")
    finally:
        batcher.stop()
        FAULTS.reset()
    return {
        "constraints": n_constraints,
        "fail_policy": "open",
        "max_queue": batcher.max_queue,
        "breaker": breaker.snapshot(),
        "phases": out,
    }


def run_slo_bench(n_requests=1800, n_constraints=20, err=sys.stderr):
    """The `--slo` replay (docs/observability.md §SLO & saturation):
    the streaming SLO engine watching a clean → device-faulted →
    recovered cycle through the decision-log seam. Reports per-phase
    live attainment/burn/saturation, the breach count (the fault phase
    must fire exactly one slo_breach flight record — hysteresis), and
    the autoscaler headline (saturation, headroom) after recovery.
    Short burn windows scale the 1 min/15 min production policy down
    to bench wall-clock; the arithmetic is identical."""
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.faults import FAULTS, CircuitBreaker
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.obs import (
        DecisionLog,
        FlightRecorder,
        SloEngine,
        SloTarget,
    )
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    from gatekeeper_tpu.obs import Tracer

    metrics = MetricsRegistry()
    client = build_chaos_client(TpuDriver(), n_constraints)
    tracer = Tracer(max_traces=128)
    decisions = DecisionLog(metrics=metrics, replica="slo-bench")
    recorder = FlightRecorder(
        tracer=tracer, metrics=metrics, decisions=decisions,
        replica="slo-bench",
    )
    # the deadline leaves room for replay queueing at this
    # concurrency: the clean phase must attain so the fault phase's
    # burn (error verdicts) is what crosses the threshold
    target = SloTarget(
        objective=0.99,
        deadline_s=1.5,
        fast_window_s=2.0,
        slow_window_s=10.0,
    )
    slo = SloEngine(
        target=target, metrics=metrics, recorder=recorder,
        replica="slo-bench",
    )
    decisions.slo = slo
    # deliberately NO circuit breaker: the chaos lane shows the
    # breaker absorbing this fault (host-oracle degraded mode keeps
    # the SLO); this lane measures the SLO plane itself, so the fault
    # must be allowed to fail requests and burn budget
    batcher = MicroBatcher(
        client, TARGET, window_ms=2.0, metrics=metrics,
        max_queue=512, decisions=decisions,
    )
    handler = BatchedValidationHandler(
        batcher, request_timeout=10, metrics=metrics,
        fail_policy="open", decision_log=decisions, tracer=tracer,
    )
    n_sub = max(300, n_requests // 6)
    out = []
    batcher.start()
    try:
        _warm_route(client)
        replay(handler, [make_request(i) for i in range(512)], 128)
        # warmup traffic out of the windows: the phases below are the
        # measurement
        slo.reset_windows()

        def run_phase(name):
            r = replay(
                handler, [make_request(i) for i in range(n_sub)], 64
            )
            snap = slo.snapshot()
            plane = snap["planes"].get("validation") or {}
            r.update(
                phase=name,
                slo_attainment=plane.get("attainment_fast"),
                burn_rate_fast=plane.get("burn_rate_fast"),
                saturation=snap["utilization"]["saturation"],
                burning=snap["burning"],
                breaches=snap["breaches"],
            )
            out.append(r)
            print(f"slo phase: {r}", file=err)

        run_phase("clean")
        # the degradation ladder absorbs a lone batch_dispatch fault
        # (the host-oracle rung still answers within deadline), so the
        # SLO stays green — correct, but this lane measures the SLO
        # plane itself. Fail BOTH rungs, like smoke_scenario's fault
        # phase: requests resolve EvaluationUnavailable ("unavailable"
        # verdict = shed), which the engine counts against the budget.
        FAULTS.arm("webhook.batch_dispatch", mode="error")
        FAULTS.arm("webhook.host_review", mode="error")
        run_phase("device_fault")
        FAULTS.reset()
        # let the fault-phase errors age out of the fast window so the
        # recovered phase measures the recovered system (and the
        # hysteresis latch clears below clear_threshold)
        time.sleep(target.fast_window_s + 0.2)
        run_phase("recovered")
    finally:
        batcher.stop()
        FAULTS.reset()
        recorder.flush(timeout=1.0)
        recorder.stop()
    snap = slo.snapshot()
    plane = snap["planes"].get("validation") or {}
    util = snap["utilization"]
    return {
        "constraints": n_constraints,
        "target": target.to_dict(),
        "phases": out,
        "slo_attainment": plane.get("attainment_slow"),
        "burn_rate_fast": plane.get("burn_rate_fast"),
        "saturation": util["saturation"],
        "headroom_rps": util["estimated_headroom_rps"],
        "burning": snap["burning"],
        "breaches": snap["breaches"],
        "error_budget_remaining": snap["error_budget_remaining"],
        "breach_records": [
            r["trigger"] for r in recorder.records()
            if r.get("trigger") == "slo_breach"
        ],
    }


def run_integrity_bench(n_requests=1800, n_constraints=20, k=3,
                        err=sys.stderr):
    """The `--integrity` lane (docs/robustness.md §Verdict integrity):
    the verdict-integrity plane through a clean → injected-SDC →
    self-test-healed cycle on partitioned dispatch. Reports the shadow
    divergence rate, the canary packing overhead (p50 delta vs the
    SAME corpus with the plane detached — canaries ride padding slots,
    so the contract is ≤3%), and the detection latency from arming the
    device bit-flip to corruption quarantine."""
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.constraint import tpudriver as _td
    from gatekeeper_tpu.faults import FAULTS, device_point
    from gatekeeper_tpu.integrity import IntegrityPlane
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.obs import DecisionLog, FlightRecorder, Tracer
    from gatekeeper_tpu.parallel.partition import PartitionDispatcher
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    metrics = MetricsRegistry()
    driver = TpuDriver()
    client = build_chaos_client(driver, n_constraints)
    tracer = Tracer(max_traces=128)
    decisions = DecisionLog(metrics=metrics, replica="integrity-bench")
    recorder = FlightRecorder(
        tracer=tracer, metrics=metrics, decisions=decisions,
        replica="integrity-bench",
    )
    disp = PartitionDispatcher(
        client, TARGET, k=k, metrics=metrics,
        failure_threshold=3, recovery_seconds=1.0,
    )
    plane = IntegrityPlane(
        metrics=metrics, decisions=decisions, recorder=recorder,
        quarantine_threshold=2, shadow_sample_n=8,
    )
    plane.attach_client(client)
    plane.attach_dispatcher(disp)
    batcher = MicroBatcher(
        client, TARGET, window_ms=2.0, metrics=metrics,
        max_queue=512, partitioner=disp, integrity=plane,
    )
    handler = BatchedValidationHandler(
        batcher, request_timeout=10, metrics=metrics,
        fail_policy="open", tracer=tracer,
    )
    n_sub = max(300, n_requests // 6)
    phases = []

    def run_phase(name, **extra):
        r = replay(
            handler, [make_request(i) for i in range(n_sub)], 64
        )
        snap = plane.snapshot()
        r.update(
            phase=name,
            canary_batches=snap["canary"]["batches"],
            canary_mismatch_batches=snap["canary"]["mismatch_batches"],
            quarantined=sorted(snap["quarantined"]),
            **extra,
        )
        phases.append(r)
        print(f"integrity phase: {r}", file=err)
        return r

    saved_min_batch = _td.MIN_DEVICE_BATCH
    _td.MIN_DEVICE_BATCH = 1  # keep micro-batches on the device path
    batcher.start()
    try:
        _warm_route(client)
        # warm with the exact phase workload (same corpus, same
        # concurrency): a different batch-shape mix would leave compile
        # buckets cold and bill them to the baseline phase
        for _ in range(2):
            replay(handler, [make_request(i) for i in range(n_sub)], 64)

        # canary overhead: the same corpus, plane detached vs attached
        # (the baseline replays first so cache warmth favors the
        # canaried run, making the reported overhead conservative)
        base = run_phase("baseline_detached")
        driver.set_integrity(plane)
        clean = run_phase("clean")
        overhead = (
            (clean["p50_ms"] - base["p50_ms"]) / base["p50_ms"]
            if base["p50_ms"] else 0.0
        )

        # injected SDC: one device's canary rows bit-flip every batch;
        # detection latency = arm -> corruption quarantine trip
        plan = disp.plan()
        sick = plan.partitions[0].device
        t_arm = time.monotonic()
        FAULTS.arm(device_point("integrity.canary", sick), mode="error")
        sdc = run_phase("injected_sdc", sick_device=sick)
        snap = plane.snapshot()
        q = snap["quarantined"].get(str(sick))
        detection_s = (
            round((time.monotonic() - t_arm) - q["for_s"], 3)
            if q else None
        )
        sdc["detection_latency_s"] = detection_s

        # heal: disarm the flip, golden self-test replays clean
        FAULTS.reset()
        healed = plane.selftest(sick)
        run_phase("selftest_healed", selftest_pass=healed)
        plane.drain_shadow()
    finally:
        _td.MIN_DEVICE_BATCH = saved_min_batch
        batcher.stop()
        plane.close()
        FAULTS.reset()
        recorder.stop()
    snap = plane.snapshot()
    sampled = snap["shadow"]["sampled"]
    return {
        "constraints": n_constraints,
        "partitions": k,
        "phases": phases,
        "divergence_rate": round(
            snap["shadow"]["divergences"] / sampled, 4
        ) if sampled else 0.0,
        "shadow_sampled": sampled,
        "canary_overhead_frac": round(overhead, 4),
        "detection_latency_s": detection_s,
        "selftest_healed": bool(healed),
        "canary": snap["canary"],
        "selftest": snap["selftest"],
    }


def _sched_request(i, cls):
    """A bench request pinned to one of two tenant namespaces: the
    25% "quiet" class (well-behaved, inside its fair share) vs the 75%
    "noisy" class (the overload driver). Both object and oldObject
    share the metadata dict, so one namespace write covers the
    decision-log tenant seam and the scheduler quota key."""
    req = make_request(i)
    ns = f"ns-{cls}"
    req["namespace"] = ns
    req["object"]["metadata"]["namespace"] = ns
    return req


def run_sched_bench(duration_s=6.0, rps=600.0, n_constraints=20,
                    err=sys.stderr):
    """The `--sched` lane (docs/operations.md §Admission scheduling):
    the SAME open-loop two-tenant overload driven first through the
    legacy FIFO queue, then through the deadline scheduler. Headline:
    the per-class attainment split (FIFO lets the noisy tenant starve
    the quiet one; the scheduler caps the noisy tenant at its fair
    share), and the shed split (predictive `predicted_miss` sheds vs
    FIFO's blind `queue_full` tail-drops).

    Overload is forced, not hoped for: the review path is throttled to
    a fixed per-row device cost (~3 ms/row ≈ 333 rps real capacity vs
    600 offered), the scheduler's cost model is floored to that same
    cost (the bench knob standing in for a warm attribution EWMA, and
    seeded into the SLO engine so saturation reads hot from t=0), and
    the scheduler's overload thresholds are lowered so the ~6 s phase
    reliably crosses them."""
    import itertools
    import threading

    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.faults import FAULTS
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.obs import DecisionLog, SloEngine, SloTarget
    from gatekeeper_tpu.sched import BatchCostModel
    from gatekeeper_tpu.soak.loadgen import run_open_loop
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    deadline_s = 0.5
    per_row_s = 3e-3
    phases = []
    for policy in ("fifo", "deadline"):
        metrics = MetricsRegistry()
        client = build_webhook_client(TpuDriver(), n_constraints)
        # throttle the review path to a fixed per-row device cost so
        # real capacity (1/per_row_s ≈ 333 rps) sits well under the
        # offered rate AND matches the scheduler's cost model exactly
        # — the predicted-miss arithmetic is judged against reality
        def throttled_review_many(reviews, _inner=client.review_many):
            time.sleep(per_row_s * len(reviews))
            return _inner(reviews)

        client.review_many = throttled_review_many
        decisions = DecisionLog(metrics=metrics, replica=f"sched-{policy}")
        target = SloTarget(
            objective=0.99,
            deadline_s=deadline_s,
            fast_window_s=2.0,
            slow_window_s=10.0,
        )
        slo = SloEngine(
            target=target, metrics=metrics, replica=f"sched-{policy}"
        )
        decisions.slo = slo
        batcher = MicroBatcher(
            client, TARGET, window_ms=2.0, metrics=metrics,
            max_queue=256, max_batch=64, decisions=decisions,
            sched_policy=policy, slo=slo,
        )
        # bench knobs (see docstring): deterministic per-row cost floor
        # so predicted-miss arithmetic has a live cost model from t=0,
        # and lowered overload thresholds so the short phase crosses
        # them; production uses the attribution-fed defaults
        batcher.sched.cost = BatchCostModel(
            slo=slo, per_row_fn=lambda: per_row_s
        )
        batcher.sched.overload_saturation = 0.5
        batcher.sched.burning_saturation = 0.4
        handler = BatchedValidationHandler(
            batcher, request_timeout=deadline_s, metrics=metrics,
            fail_policy="open", decision_log=decisions,
        )
        counter = itertools.count()
        lock = threading.Lock()
        per_class = {"quiet": [], "noisy": []}

        def submit(plane):
            i = next(counter)
            cls = "quiet" if i % 4 == 0 else "noisy"
            req = _sched_request(i, cls)
            t0 = time.perf_counter()
            try:
                resp = handler.handle(req)
                status = 200
                outcome = "ok" if resp.allowed else "denied"
            except Exception:
                status, outcome = 500, "conn_error"
            lat = time.perf_counter() - t0
            with lock:
                per_class[cls].append(lat)
            return status, outcome

        batcher.start()
        try:
            _warm_route(client)
            replay(
                handler,
                [_sched_request(i, "noisy" if i % 4 else "quiet")
                 for i in range(128)],
                32,
            )
            slo.reset_windows()
            # seed the saturation signal with the throttled cost so the
            # feedback loop reads hot from the first arrivals
            slo.note_cost(per_row_s, rows=1)
            # warmup traffic already hit the decision log; the phase's
            # per-class split is the DELTA against this baseline
            base = decisions.tenant_stats()
            per_class["quiet"].clear()
            per_class["noisy"].clear()
            load = run_open_loop(
                submit, rps=rps, duration_s=duration_s,
                deadline_s=deadline_s, seed=99,
            )
        finally:
            batcher.stop()
            FAULTS.reset()
        stats = decisions.tenant_stats()
        classes = {}
        for cls in ("quiet", "noisy"):
            key = f"validation/ns-{cls}"
            row = stats.get(key) or {}
            b = base.get(key) or {}
            cnt = row.get("count", 0) - b.get("count", 0)
            ok = row.get("ok", 0) - b.get("ok", 0)
            shed = row.get("shed", 0) - b.get("shed", 0)
            lats = per_class[cls]
            classes[cls] = {
                "requests": cnt,
                "ok": ok,
                "shed": shed,
                "attainment": round(ok / cnt, 4) if cnt else None,
                "p50_ms": (
                    round(float(np.percentile(lats, 50)) * 1e3, 2)
                    if lats else None
                ),
                "p99_ms": (
                    round(float(np.percentile(lats, 99)) * 1e3, 2)
                    if lats else None
                ),
            }
        snap = batcher.sched.snapshot()
        phase = {
            "phase": policy,
            "generated": load.generated,
            "achieved_rps": load.achieved_rps,
            "open_loop_attainment": round(load.slo_attainment(), 4),
            "classes": classes,
            "sheds": snap["sheds"],
            "admitted": snap["admitted"],
            "overloaded": snap["overloaded"],
            "saturation": snap["saturation"],
            "tenants": snap["tenants"],
        }
        phases.append(phase)
        print(f"sched phase: {policy} classes={classes} "
              f"sheds={snap['sheds']}", file=err)

    fifo, dl = phases[0], phases[1]
    atts = [
        c["attainment"] for c in dl["classes"].values()
        if c["attainment"] is not None
    ]
    return {
        "constraints": n_constraints,
        "target_rps": rps,
        "duration_s": duration_s,
        "deadline_s": deadline_s,
        "phases": phases,
        # headline: the deadline phase's per-class split, the worst
        # per-tenant attainment under the scheduler (bench_compare
        # watches it down-bad), and predictive vs blind shed counts
        "quiet_p50_ms": dl["classes"]["quiet"]["p50_ms"],
        "quiet_p99_ms": dl["classes"]["quiet"]["p99_ms"],
        "noisy_p50_ms": dl["classes"]["noisy"]["p50_ms"],
        "noisy_p99_ms": dl["classes"]["noisy"]["p99_ms"],
        "quiet_attainment": dl["classes"]["quiet"]["attainment"],
        "noisy_attainment": dl["classes"]["noisy"]["attainment"],
        "tenant_attainment_min": min(atts) if atts else None,
        "predicted_miss_shed": dl["sheds"].get("predicted_miss", 0),
        "blind_shed": fifo["sheds"].get("queue_full", 0),
    }


def build_ingest_client(driver, n_constraints):
    """Policy load for the --ingest lane: real templates from the
    reference mix, constraints matched AWAY from the request stream
    (apps/Deployment kinds vs Pod requests). The lane measures the
    FRONT DOOR — transport, HTTP parse, decode — so every phase pays
    the identical, minimal verdict cost and the transports are the
    only variable. Violating corpora belong to the verdict lanes."""
    from gatekeeper_tpu.constraint import Backend, K8sValidationTarget

    mix = _webhook_mix()
    client = Backend(driver).new_client(K8sValidationTarget())
    for doc, _kind, _params in mix:
        client.add_template(doc)
    for i in range(n_constraints):
        _doc, kind, params = mix[i % len(mix)]
        spec = {"match": {
            "kinds": [{"apiGroups": ["apps"], "kinds": ["Deployment"]}],
        }}
        if params is not None:
            spec["parameters"] = params
        client.add_constraint(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind,
                "metadata": {"name": f"ing{i}"},
                "spec": spec,
            }
        )
    return client


def _open_loop_phase(load, deadline_s, conns_opened):
    """One phase row from an OpenLoopLoad: goodput (completions inside
    the shared deadline per offered second), attainment, latency
    percentiles over COMPLETED requests (late completions included —
    hiding them would flatter a collapsing transport), and connection
    amortization (conns opened per 1k completions)."""
    done = [s.latency_s for s in load.samples
            if s.outcome in ("ok", "denied")]
    ok = sum(1 for s in load.samples if s.ok_within(deadline_s))
    dur = load.duration_s or 1.0
    return {
        "offered_rps": load.target_rps,
        "achieved_rps": load.achieved_rps,
        "generated": load.generated,
        "completed": len(done),
        "ok_within_deadline": ok,
        "rps_sustained": round(ok / dur, 1),
        "attainment": round(load.slo_attainment(), 4),
        "p50_ms": (round(float(np.percentile(done, 50)) * 1e3, 2)
                   if done else None),
        "p99_ms": (round(float(np.percentile(done, 99)) * 1e3, 2)
                   if done else None),
        "conns": conns_opened,
        "conns_per_1k": (round(conns_opened * 1000.0 / len(done), 1)
                         if done else None),
    }


def run_ingest_bench(duration_s=6.0, rps=1500.0, n_constraints=20,
                     deadline_s=1.0, err=sys.stderr):
    """The `--ingest` lane (docs/ingest.md): the SAME open-loop Poisson
    arrival schedule driven through the front doors of one live
    WebhookServer —

      http1      conn-per-request HTTP/1 (`Connection: close`), the
                 reference webhook's worst case
      keepalive  persistent HTTP/1.1 connections on the same port
      framed     the stream listener, length-prefixed frames over a
                 small pool of multiplexed connections

    Matched load is the point: arrivals never slow down for a
    struggling transport (run_open_loop's coordinated-omission rule),
    so a front door that can't keep up shows up as missed deadlines —
    rps_sustained counts only completions inside the shared deadline.
    The three transport phases share one decoder (the C json parser)
    so transport is the only variable; a fourth phase reruns the
    framed plane with the zero-copy scanner to price decode routes on
    the wire, at a rate inside the scanner's capacity so the number
    is a decode cost, not an overload artifact. A decode micro-bench
    reports scanner vs json.loads latency and the fallback count over
    the live body corpus."""
    import http.client
    import json as _json
    import threading

    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.ingest import PLANE_VALIDATE, StreamClient
    from gatekeeper_tpu.ingest.decode import decode_review, scan_review
    from gatekeeper_tpu.soak.loadgen import run_open_loop
    from gatekeeper_tpu.webhook import WebhookServer

    client = build_ingest_client(TpuDriver(), n_constraints)
    bodies = [
        _json.dumps({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": make_request(i),
        }).encode("utf-8")
        for i in range(512)
    ]

    # -- decode micro-bench: the scanner priced against json.loads on
    # the exact bodies the phases replay, plus the parity/fallback
    # sweep (every body must take the zerocopy route)
    fallbacks = 0
    for body in bodies:
        _rev, route, _reason = decode_review(body)
        if route != "zerocopy":
            fallbacks += 1
    scan_lat, loads_lat = [], []
    for _pass in range(4):
        for body in bodies[:128]:
            t0 = time.perf_counter()
            scan_review(body)
            scan_lat.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            _json.loads(body)
            loads_lat.append(time.perf_counter() - t0)
    decode = {
        "corpus": len(bodies),
        "fallbacks": fallbacks,
        "zerocopy_p50_ms": round(
            float(np.percentile(scan_lat, 50)) * 1e3, 4),
        "zerocopy_p99_ms": round(
            float(np.percentile(scan_lat, 99)) * 1e3, 4),
        "json_p50_ms": round(
            float(np.percentile(loads_lat, 50)) * 1e3, 4),
    }
    print(f"ingest decode: {decode}", file=err)

    server = WebhookServer(
        client, TARGET, window_ms=2.0, ingest=True,
        ingest_decode="json",
    )
    server.start()
    phases = []
    try:
        port = server.port
        ingest_port = server.ingest.port
        _warm_route(client)

        def _http_submit(body, conn):
            conn.request(
                "POST", "/v1/admit", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return resp.status, "conn_error"
            allowed = _json.loads(data)["response"].get("allowed")
            return 200, "ok" if allowed else "denied"

        def phase_http1(i_counter, conns):
            def submit(plane):
                i = next(i_counter)
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=deadline_s + 2.0)
                with conns[1]:
                    conns[0] += 1
                try:
                    # one TCP connection per request: the legacy
                    # conn-per-request shape (Connection: close)
                    conn.request(
                        "POST", "/v1/admit", body=bodies[i % 512],
                        headers={
                            "Content-Type": "application/json",
                            "Connection": "close",
                        },
                    )
                    resp = conn.getresponse()
                    data = resp.read()
                finally:
                    conn.close()
                if resp.status != 200:
                    return resp.status, "conn_error"
                allowed = _json.loads(data)["response"].get("allowed")
                return 200, "ok" if allowed else "denied"
            return submit

        def phase_keepalive(i_counter, conns, tl, pool):
            def submit(plane):
                i = next(i_counter)
                conn = getattr(tl, "conn", None)
                if conn is None:
                    conn = tl.conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=deadline_s + 2.0)
                    with conns[1]:
                        conns[0] += 1
                        pool.append(conn)
                try:
                    return _http_submit(bodies[i % 512], conn)
                except Exception:
                    # a dropped persistent conn re-opens on the next
                    # arrival; this one is the transport's miss
                    try:
                        conn.close()
                    finally:
                        tl.conn = None
                    raise
            return submit

        def phase_framed(i_counter, conns, tl, pool):
            def submit(plane):
                i = next(i_counter)
                c = getattr(tl, "client", None)
                if c is None:
                    c = tl.client = StreamClient(
                        "127.0.0.1", ingest_port)
                    with conns[1]:
                        conns[0] += 1
                        pool.append(c)
                status, data = c.request(
                    bodies[i % 512], PLANE_VALIDATE,
                    budget_ms=int(deadline_s * 1000) + 2000,
                    timeout=deadline_s + 2.0,
                )
                if status != 200:
                    return status, "conn_error"
                allowed = _json.loads(data)["response"].get("allowed")
                return 200, "ok" if allowed else "denied"
            return submit

        plan = [
            # (phase, offered rps, workers, ingest decode route)
            ("http1", rps, 256, None),
            ("keepalive", rps, 128, None),
            ("framed", rps, 64, "json"),
            # the scanner priced ON the wire, inside its capacity:
            # overload collapse would drown the decode signal
            ("framed_zerocopy", min(rps, 600.0), 64, "zerocopy"),
        ]
        for name, offered, workers, decode_route in plan:
            import itertools

            i_counter = itertools.count()
            conns = [0, threading.Lock()]
            pool: list = []
            tl = threading.local()
            if decode_route is not None:
                server.ingest.decode = decode_route
            if name == "http1":
                submit = phase_http1(i_counter, conns)
            elif name == "keepalive":
                submit = phase_keepalive(i_counter, conns, tl, pool)
            else:
                submit = phase_framed(i_counter, conns, tl, pool)
            # per-phase warm: route + transport handshakes out of the
            # measured window
            for _ in range(8):
                try:
                    submit("validation")
                except Exception:
                    pass
            stats0 = server.ingest.stats()["decode"]
            load = run_open_loop(
                submit, rps=offered, duration_s=duration_s,
                deadline_s=deadline_s, seed=1311,
                max_workers=workers,
            )
            row = _open_loop_phase(load, deadline_s, conns[0])
            row["phase"] = name
            stats1 = server.ingest.stats()["decode"]
            row["decode_routes"] = {
                k: stats1[k] - stats0.get(k, 0) for k in stats1
                if stats1[k] != stats0.get(k, 0)
            }
            for c in pool:
                try:
                    c.close()
                except Exception:
                    pass
            phases.append(row)
            print(f"ingest phase: {name} offered={offered} "
                  f"rps_sustained={row['rps_sustained']} "
                  f"attainment={row['attainment']} "
                  f"p99={row['p99_ms']}ms conns={row['conns']}",
                  file=err)
        ingest_stats = server.ingest.stats()
    finally:
        server.stop()

    by = {p["phase"]: p for p in phases}
    framed, http1 = by["framed"], by["http1"]
    ratio = (
        round(framed["rps_sustained"] / http1["rps_sustained"], 2)
        if http1["rps_sustained"] else None
    )
    # share of the framed request's end-to-end p50 spent decoding (the
    # zero-copy scanner, measured on the live corpus): the `ingest_decode`
    # span's budget share
    span_share = (
        round(decode["zerocopy_p50_ms"] / framed["p50_ms"], 4)
        if framed["p50_ms"] else None
    )
    return {
        "constraints": n_constraints,
        "offered_rps": rps,
        "duration_s": duration_s,
        "deadline_s": deadline_s,
        "phases": phases,
        "decode": decode,
        "ingest_stats": ingest_stats,
        # headline: framed goodput at matched offered load vs the
        # legacy conn-per-request phase, under one shared deadline
        "rps_sustained": framed["rps_sustained"],
        "framed_vs_http1": ratio,
        "http1_rps_sustained": http1["rps_sustained"],
        "keepalive_rps_sustained": by["keepalive"]["rps_sustained"],
        "framed_attainment": framed["attainment"],
        "http1_attainment": http1["attainment"],
        "p50_ms": framed["p50_ms"],
        "p99_ms": framed["p99_ms"],
        "decode_p50_ms": decode["zerocopy_p50_ms"],
        "decode_span_share": span_share,
        "conns_per_1k_framed": framed["conns_per_1k"],
        "conns_per_1k_http1": http1["conns_per_1k"],
    }


def build_partition_client(driver, n_constraints):
    """Policy load for the --partitions lane: ONE template, n
    constraints named w000..wNNN (zero-padded so the driver's sorted
    identity order is numeric), constraint j matching ONLY namespace
    part-ns-<j % 4>. Round-robin partitioning over the sorted identity
    list puts global index j in partition j % k — so with k=4 every
    partition's constraints match exactly one namespace, and the bench
    can address one fault domain with one namespace."""
    from gatekeeper_tpu.constraint import Backend, K8sValidationTarget

    client = Backend(driver).new_client(K8sValidationTarget())
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "partbench"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "PartBench"}}},
            "targets": [{
                "target": TARGET,
                "rego": _CHAOS_REGO.replace("chaosbench", "partbench"),
            }],
        },
    })
    for i in range(n_constraints):
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "PartBench",
            "metadata": {"name": f"w{i:03d}"},
            "spec": {"match": {
                "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                "namespaces": [f"part-ns-{i % 4}"],
            }},
        })
    return client


def part_request(i, ns_idx, violating=True):
    r = make_request(i, violating)
    ns = f"part-ns-{ns_idx}"
    r["namespace"] = ns
    r["object"]["metadata"]["namespace"] = ns
    if "oldObject" in r:
        r["oldObject"]["metadata"]["namespace"] = ns
    return r


def _normalize_results(results):
    return [
        (
            r.constraint.get("kind"),
            (r.constraint.get("metadata") or {}).get("name"),
            r.msg,
        )
        for r in results
    ]


def run_partitions_bench(n_requests=2000, n_constraints=40, k=4,
                         err=sys.stderr):
    """The `--partitions` lane (docs/robustness.md §Fault domains):
    partitioned program dispatch with per-device breakers and
    quarantine. Phases: per-partition fused latency, a healthy-subsets
    phase with ONE device faulted (requests matching only healthy
    partitions must show ZERO degraded dispatches), a mixed sick-device
    phase (degraded coverage fraction + time to re-homed full fused
    coverage), and post-disarm recovery (probe heals, plan restores
    home devices). Also spot-checks partition parity: merged
    per-partition verdicts == the monolithic dispatch."""
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.faults import FAULTS, device_point
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.parallel.partition import (
        PartitionDispatcher,
        merge_partition_results,
    )
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    metrics = MetricsRegistry()
    client = build_partition_client(TpuDriver(), n_constraints)
    disp = PartitionDispatcher(
        client, TARGET, k=k, metrics=metrics,
        failure_threshold=3, recovery_seconds=1.0,
    )
    batcher = MicroBatcher(
        client, TARGET, window_ms=2.0, metrics=metrics,
        max_queue=512, partitioner=disp,
    )
    handler = BatchedValidationHandler(
        batcher, request_timeout=10, metrics=metrics, fail_policy="open"
    )
    n_sub = max(256, n_requests // 6)
    phases = []
    deg_key = 'webhook_degraded_dispatch_total{plane="validation"}'

    def run_phase(name, requests, concurrency=64):
        d0 = dict(disp.dispatches)
        deg0 = metrics.snapshot()["counters"].get(deg_key, 0)
        r = replay(handler, requests, concurrency)
        d1 = disp.dispatches
        deltas = {
            route: d1.get(route, 0) - d0.get(route, 0)
            for route in ("fused", "host", "failed", "skipped")
        }
        total = deltas["fused"] + deltas["host"] + deltas["failed"]
        r.update(
            phase=name,
            partition_dispatches=deltas,
            degraded_dispatches=(
                metrics.snapshot()["counters"].get(deg_key, 0) - deg0
            ),
            degraded_coverage_fraction=round(
                (deltas["host"] + deltas["failed"]) / total, 4
            ) if total else 0.0,
            quarantined=list(disp.snapshot()["quarantined"]),
        )
        phases.append(r)
        print(f"partitions phase: {r}", file=err)
        return r

    def mixed(n, start=0):
        return [part_request(start + i, i % 4) for i in range(n)]

    batcher.start()
    try:
        _warm_route(client)
        plan = disp.plan()
        for p in plan.partitions:
            disp.ensure_staged(p)
        # warm each partition's sub-program kernels off the clock
        warm_reviews = [
            batcher.target_handler.augment_request(r)
            for r in mixed(32)
        ]
        for p in plan.partitions:
            client.review_many_subset(warm_reviews, p.subset,
                                      device=p.device)
        # parity spot check: merged partitioned == monolithic, request
        # by request (the full property battery lives in the chaos lane)
        mono = client.review_many(warm_reviews)
        per_part = [
            client.review_many_subset(warm_reviews, p.subset,
                                      device=p.device)
            for p in plan.partitions
        ]
        parity_ok = True
        for i in range(len(warm_reviews)):
            merged = merge_partition_results(
                [
                    (pp[i].by_target.get(TARGET).results
                     if TARGET in pp[i].by_target else [])
                    for pp in per_part
                ],
                plan.order,
            )
            expect = (
                mono[i].by_target[TARGET].results
                if TARGET in mono[i].by_target else []
            )
            if _normalize_results(merged) != _normalize_results(expect):
                parity_ok = False
        # per-partition fused latency (direct subset dispatch)
        per_partition = []
        for p in plan.partitions:
            lat = []
            for _ in range(12):
                t0 = time.perf_counter()
                client.review_many_subset(warm_reviews, p.subset,
                                          device=p.device)
                lat.append(time.perf_counter() - t0)
            per_partition.append({
                "partition": p.index,
                "device": p.device,
                "constraints": len(p.keys),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            })
            print(f"partition {p.index}: {per_partition[-1]}", file=err)

        run_phase("fused_healthy", mixed(n_sub))
        # ONE device sick; requests that match only HEALTHY partitions
        # must pay nothing: zero degraded dispatches, zero host routes
        FAULTS.arm(device_point("driver.device_dispatch", 1),
                   mode="error")
        t_fault = time.monotonic()
        healthy = run_phase(
            "sick_device_healthy_subsets",
            [part_request(i, [0, 2, 3][i % 3]) for i in range(n_sub)],
        )
        # mixed traffic: ns-1's subset degrades to host, the device-1
        # breaker trips, quarantine re-homes its partition, and full
        # fused coverage returns while the chip is still sick
        recovery_s = None
        waves = 0
        fault0 = dict(disp.dispatches)
        while waves < 40:
            d0 = dict(disp.dispatches)
            replay(handler, mixed(128, start=waves * 128), 64)
            waves += 1
            degraded = (
                disp.dispatches.get("host", 0) - d0.get("host", 0)
                + disp.dispatches.get("failed", 0) - d0.get("failed", 0)
            )
            if degraded == 0:
                recovery_s = round(time.monotonic() - t_fault, 3)
                break
        fault1 = dict(disp.dispatches)
        fault_deltas = {
            route: fault1.get(route, 0) - fault0.get(route, 0)
            for route in ("fused", "host", "failed")
        }
        fault_total = sum(fault_deltas.values())
        fault_coverage = (
            round(
                (fault_deltas["host"] + fault_deltas["failed"])
                / fault_total, 4,
            )
            if fault_total else 0.0
        )
        run_phase("sick_device_rehomed", mixed(n_sub))
        # disarm: the quarantined device's half-open probe heals it and
        # the plan restores the home assignment
        FAULTS.reset()
        time.sleep(1.2)
        run_phase("recovered", mixed(n_sub))
        restored = all(
            p.device == p.home_device
            for p in disp.plan().partitions
        )
    finally:
        batcher.stop()
        disp.close()
        FAULTS.reset()
    return {
        "partitions": k,
        "constraints": n_constraints,
        "plan": disp.snapshot()["plan"],
        "per_partition": per_partition,
        "parity_ok": parity_ok,
        "healthy_subset_degraded": (
            healthy["degraded_dispatches"]
            + healthy["partition_dispatches"]["host"]
        ),
        "degraded_coverage_fraction": fault_coverage,
        "recovery_s": recovery_s,
        "home_restored": restored,
        "dispatcher": disp.snapshot(),
        "phases": phases,
    }


_CHURN_BENCH_REGO = """package churnbench{n}

violation[{{"msg": msg}}] {{
    input.review.object.spec.containers[_].securityContext.privileged
    msg := "churn{n}: privileged container"
}}
"""


def run_churn_bench(n_requests=600, wave_sizes=(10, 50, 500), k=4,
                    err=sys.stderr):
    """The `--churn` lane (docs/compile.md §Bench): template ingest
    waves against a partitioned plan under live admission load. Per
    wave size it reports ingest-to-serve latency (first template add ->
    every partition's swapped program serving fused again) plus the
    zero-downtime counters: degraded dispatches and in-process 5xx
    (handler exceptions) during the wave must both be zero — in-flight
    batches ride the old programs or the host rung while the shadow
    slot compiles."""
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.parallel.partition import PartitionDispatcher
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    metrics = MetricsRegistry()
    client = build_partition_client(TpuDriver(), 16)
    driver = client._driver
    disp = PartitionDispatcher(
        client, TARGET, k=k, metrics=metrics,
        failure_threshold=3, recovery_seconds=1.0,
    )
    batcher = MicroBatcher(
        client, TARGET, window_ms=2.0, metrics=metrics,
        max_queue=512, partitioner=disp,
    )
    handler = BatchedValidationHandler(
        batcher, request_timeout=10, metrics=metrics, fail_policy="open"
    )
    deg_key = 'webhook_degraded_dispatch_total{plane="validation"}'

    def replay_counting(requests, concurrency=64):
        """replay() that counts handler exceptions — what the HTTP
        plane would surface as 5xx — instead of propagating them."""
        lat = np.zeros(len(requests))
        errs = np.zeros(len(requests), bool)

        def one(i):
            t0 = time.perf_counter()
            try:
                handler.handle(requests[i])
            except Exception:
                errs[i] = True
            lat[i] = time.perf_counter() - t0

        with ThreadPoolExecutor(max_workers=concurrency) as ex:
            list(ex.map(one, range(len(requests))))
        return {
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            "http_5xx": int(errs.sum()),
        }

    def all_ready():
        plan = disp.plan()
        ready = getattr(driver, "subset_ready", None)
        if ready is None:
            return True
        return all(ready(TARGET, p.subset) for p in plan.partitions)

    def mixed(n, start=0):
        return [part_request(start + i, i % 4) for i in range(n)]

    churn_n = 0
    waves = []
    batcher.start()
    try:
        _warm_route(client)
        for p in disp.plan().partitions:
            disp.ensure_staged(p)
        replay_counting(mixed(max(128, n_requests // 4)))
        for wave in wave_sizes:
            deg0 = metrics.snapshot()["counters"].get(deg_key, 0)
            c0 = getattr(driver, "program_compiles", 0)
            s0 = getattr(driver, "subset_swaps", 0)
            cf0 = getattr(driver, "subset_carryforwards", 0)
            http_5xx = 0
            t0 = time.perf_counter()
            for _ in range(wave):
                churn_n += 1
                kind = f"ChurnBench{churn_n}"
                client.add_template({
                    "apiVersion": "templates.gatekeeper.sh/v1beta1",
                    "kind": "ConstraintTemplate",
                    "metadata": {"name": kind.lower()},
                    "spec": {
                        "crd": {"spec": {"names": {"kind": kind}}},
                        "targets": [{
                            "target": TARGET,
                            "rego": _CHURN_BENCH_REGO.format(n=churn_n),
                        }],
                    },
                })
                client.add_constraint({
                    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                    "kind": kind,
                    "metadata": {"name": f"wave-{churn_n}"},
                    "spec": {"match": {
                        "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                        "namespaces": [f"part-ns-{churn_n % 4}"],
                    }},
                })
            # serve through the churn: traffic keeps flowing while the
            # changed partitions shadow-compile and swap; readiness is
            # every partition of the NEW plan serving its new program
            ingest_to_serve_ms = None
            rounds = 0
            while rounds < 120:
                r = replay_counting(mixed(128, start=rounds * 128))
                http_5xx += r["http_5xx"]
                rounds += 1
                if all_ready():
                    ingest_to_serve_ms = round(
                        (time.perf_counter() - t0) * 1e3, 1
                    )
                    break
            steady = replay_counting(mixed(max(128, n_requests // 4)))
            http_5xx += steady["http_5xx"]
            row = {
                "wave": wave,
                "ingest_to_serve_ms": ingest_to_serve_ms,
                "degraded_dispatches": (
                    metrics.snapshot()["counters"].get(deg_key, 0) - deg0
                ),
                "http_5xx": http_5xx,
                "compiles": getattr(driver, "program_compiles", 0) - c0,
                "swaps": getattr(driver, "subset_swaps", 0) - s0,
                "carryforwards": (
                    getattr(driver, "subset_carryforwards", 0) - cf0
                ),
                "serve_rounds": rounds,
                "steady_p50_ms": steady["p50_ms"],
                "steady_p99_ms": steady["p99_ms"],
            }
            waves.append(row)
            print(f"churn wave: {row}", file=err)
    finally:
        batcher.stop()
        disp.close()
    return {
        "partitions": k,
        "waves": waves,
        "ingest_to_serve_ms": (
            waves[-1]["ingest_to_serve_ms"] if waves else None
        ),
        "degraded_dispatches": sum(
            w["degraded_dispatches"] for w in waves
        ),
        "http_5xx": sum(w["http_5xx"] for w in waves),
        "compiles": sum(w["compiles"] for w in waves),
        "swaps": sum(w["swaps"] for w in waves),
        "compile_plane": (
            driver.compile_plane_stats()
            if hasattr(driver, "compile_plane_stats") else None
        ),
    }


_EXTERNAL_REGO = """package externalbench

violation[{"msg": msg}] {
    images := [img | img := input.review.object.spec.containers[_].image]
    response := external_data({"provider": "bench-provider", "keys": images})
    count(response.errors) > 0
    msg := sprintf("verification failed: %v", [response.errors])
}
"""


class _StubProviderHTTP:
    """Stdlib stub provider for the --external lane: answers the
    ProviderRequest protocol, counts every outbound fetch (the
    batching-contract number this bench reports), and marks keys
    containing "bad" with an error entry."""

    def __init__(self, latency_s=0.0):
        import json as _json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.fetches = 0
        self.keys_fetched = 0
        self.latency_s = latency_s
        outer = self

        class _H(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                body = _json.loads(self.rfile.read(n) or b"{}")
                keys = ((body.get("request") or {}).get("keys")) or []
                outer.fetches += 1
                outer.keys_fetched += len(keys)
                if outer.latency_s:
                    time.sleep(outer.latency_s)
                payload = _json.dumps({
                    "response": {
                        "items": [
                            {"key": k, "error": "unsigned"}
                            if "bad" in k
                            else {"key": k, "value": f"ok:{k}"}
                            for k in keys
                        ],
                        "systemError": "",
                    }
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/v"
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        ).start()

    def stop(self):
        self._httpd.shutdown()


def run_external_bench(n_requests=3000, n_keys=7, err=sys.stderr):
    """The `--external` replay (docs/externaldata.md): admission load
    whose policy consults an external-data provider through the batch
    plane. Reports p50/p99, cache hit rate, and fetches-per-batch —
    the numbers that prove lookups ride the micro-batch instead of
    breaking it (steady state: hit rate -> 1.0, fetches/batch -> 0)."""
    import threading

    from gatekeeper_tpu.constraint import (
        Backend,
        K8sValidationTarget,
        TpuDriver,
    )
    from gatekeeper_tpu.externaldata import ExternalDataSystem
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    stub = _StubProviderHTTP()
    metrics = MetricsRegistry()
    system = ExternalDataSystem(metrics=metrics)
    system.upsert({
        "apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
        "kind": "Provider",
        "metadata": {"name": "bench-provider"},
        "spec": {
            "url": stub.url,
            "timeout": 5,
            "failurePolicy": "Ignore",
            "cacheTTLSeconds": 3600,
            "negativeCacheTTLSeconds": 3600,
        },
    })
    client = Backend(TpuDriver()).new_client(K8sValidationTarget())
    client.set_external_data(system)
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "externalbench"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "ExternalBench"}}},
            "targets": [{"target": TARGET, "rego": _EXTERNAL_REGO}],
        },
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "ExternalBench",
        "metadata": {"name": "eb"},
        "spec": {"match": {"kinds": [
            {"apiGroups": [""], "kinds": ["Pod"]}
        ]}},
    })

    def ext_request(i, violating=False):
        r = make_request(i, violating=False)
        key = f"bad.img/{i % n_keys}" if violating else f"reg.example/app{i % n_keys}"
        r["object"]["spec"]["containers"][0]["image"] = key
        return r

    batcher = MicroBatcher(client, TARGET, window_ms=2.0, metrics=metrics)
    handler = BatchedValidationHandler(
        batcher, request_timeout=30, metrics=metrics
    )
    out = []
    batcher.start()
    try:
        _warm_route(client)

        def run_phase(name, violating):
            f0, b0 = system.fetch_count, batcher.batches_dispatched
            snap0 = metrics.snapshot()["counters"]
            lk = "externaldata_cache_lookups_total"
            hits0 = sum(
                v for k, v in snap0.items()
                if k.startswith(lk) and 'result="hit"' in k
            )
            total0 = sum(
                v for k, v in snap0.items() if k.startswith(lk)
            )
            r = replay(
                handler,
                [ext_request(i, violating) for i in range(max(400, n_requests // 3))],
                128,
            )
            snap1 = metrics.snapshot()["counters"]
            hits1 = sum(
                v for k, v in snap1.items()
                if k.startswith(lk) and 'result="hit"' in k
            )
            total1 = sum(
                v for k, v in snap1.items() if k.startswith(lk)
            )
            batches = max(1, batcher.batches_dispatched - b0)
            r.update(
                phase=name,
                fetches=system.fetch_count - f0,
                fetches_per_batch=round(
                    (system.fetch_count - f0) / batches, 3
                ),
                cache_hit_rate=round(
                    (hits1 - hits0) / max(1, total1 - total0), 4
                ),
            )
            out.append(r)
            print(f"external phase: {r}", file=err)

        run_phase("cold_allow", violating=False)
        run_phase("warm_allow", violating=False)
        run_phase("warm_deny", violating=True)
    finally:
        batcher.stop()
        stub.stop()
    return {
        "keys": n_keys,
        "provider_fetches": system.fetch_count,
        "provider_keys_fetched": stub.keys_fetched,
        "stale_serves": system.stale_serves,
        "phases": out,
    }


def run_fleet_bench(n_requests=1200, n_keys=24, err=sys.stderr):
    """The `--fleet` replay (docs/fleet.md): cold-fetch amplification
    of the external-data plane as the webhook scales horizontally. A
    load balancer spreads identical traffic over every replica, so
    WITHOUT the fleet cache plane each of N replicas pays its own cold
    fetch per key — amplification N. WITH the plane, the first replica
    to fetch publishes and peers merge: amplification stays ~1.

    Phases: n1 (one replica, the floor), n2_isolated (two replicas, no
    fleet — the regression this subsystem removes), n2_fleet (two
    replicas gossiping through one FakeCluster). Reports fetches per
    key for each and the headline cold_fetch_amplification ratio."""
    from gatekeeper_tpu.constraint import (
        Backend,
        K8sValidationTarget,
        TpuDriver,
    )
    from gatekeeper_tpu.control.events import FakeCluster
    from gatekeeper_tpu.externaldata import ExternalDataSystem
    from gatekeeper_tpu.fleet import FleetPlane
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    stub = _StubProviderHTTP()

    def build_replica(fleet_plane=None):
        metrics = MetricsRegistry()
        system = ExternalDataSystem(metrics=metrics)
        if fleet_plane is not None:
            fleet_plane.attach_cache(system)
        system.upsert({
            "apiVersion": "externaldata.gatekeeper.sh/v1alpha1",
            "kind": "Provider",
            "metadata": {"name": "bench-provider"},
            "spec": {
                "url": stub.url,
                "timeout": 5,
                "failurePolicy": "Ignore",
                "cacheTTLSeconds": 3600,
                "negativeCacheTTLSeconds": 3600,
            },
        })
        client = Backend(TpuDriver()).new_client(K8sValidationTarget())
        client.set_external_data(system)
        client.add_template({
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "externalbench"},
            "spec": {
                "crd": {"spec": {"names": {"kind": "ExternalBench"}}},
                "targets": [{"target": TARGET, "rego": _EXTERNAL_REGO}],
            },
        })
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "ExternalBench",
            "metadata": {"name": "eb"},
            "spec": {"match": {"kinds": [
                {"apiGroups": [""], "kinds": ["Pod"]}
            ]}},
        })
        batcher = MicroBatcher(
            client, TARGET, window_ms=2.0, metrics=metrics
        )
        handler = BatchedValidationHandler(
            batcher, request_timeout=30, metrics=metrics
        )
        batcher.start()
        return system, batcher, handler

    def ext_request(i):
        r = make_request(i, violating=False)
        r["object"]["spec"]["containers"][0]["image"] = (
            f"reg.example/app{i % n_keys}"
        )
        return r

    phases = []

    def run_phase(name, handlers, planes=()):
        """Drive every handler with the SAME key universe (the load-
        balancer model) and count fleet-wide outbound fetches."""
        f0 = stub.fetches
        n_sub = max(n_keys * 4, n_requests // max(1, len(handlers)))
        rows = []
        for j, handler in enumerate(handlers):
            if j > 0 and planes:
                # the LB does not barrier on gossip, but a steady-state
                # fleet has had a publish interval between cold bursts;
                # give the plane one propagation window
                deadline = time.monotonic() + 5.0
                while (
                    time.monotonic() < deadline
                    and planes[j].cache_merged < n_keys
                ):
                    time.sleep(0.01)
            rows.append(
                replay(handler, [ext_request(i) for i in range(n_sub)], 64)
            )
        fetches = stub.fetches - f0
        r = {
            "phase": name,
            "replicas": len(handlers),
            "keys": n_keys,
            "fetches": fetches,
            "fetches_per_key": round(fetches / n_keys, 3),
            "p50_ms": max(row["p50_ms"] for row in rows),
            "p99_ms": max(row["p99_ms"] for row in rows),
        }
        phases.append(r)
        print(f"fleet phase: {r}", file=err)
        return r

    # n1: one replica — the cold-fetch floor (1 fetch per key)
    sys1, b1, h1 = build_replica()
    try:
        _warm_route(b1.client)
        r1 = run_phase("n1", [h1])
    finally:
        b1.stop()

    # n2_isolated: two replicas, no fleet — every replica re-pays
    stub.fetches = 0
    sys_a, b_a, h_a = build_replica()
    sys_b, b_b, h_b = build_replica()
    try:
        _warm_route(b_a.client)
        r2i = run_phase("n2_isolated", [h_a, h_b])
    finally:
        b_a.stop()
        b_b.stop()

    # n2_fleet: two replicas gossiping through one cluster
    stub.fetches = 0
    cluster = FakeCluster()
    p_a = FleetPlane(cluster, "bench-a", publish_interval_s=0.02)
    p_b = FleetPlane(cluster, "bench-b", publish_interval_s=0.02)
    sys_fa, b_fa, h_fa = build_replica(p_a)
    sys_fb, b_fb, h_fb = build_replica(p_b)
    p_a.start()
    p_b.start()
    try:
        _warm_route(b_fa.client)
        r2f = run_phase(
            "n2_fleet", [h_fa, h_fb], planes=[p_a, p_b]
        )
    finally:
        p_a.stop()
        p_b.stop()
        b_fa.stop()
        b_fb.stop()
        stub.stop()

    return {
        "keys": n_keys,
        "phases": phases,
        "fetches_per_key_n1": r1["fetches_per_key"],
        "fetches_per_key_n2_isolated": r2i["fetches_per_key"],
        "fetches_per_key_n2_fleet": r2f["fetches_per_key"],
        # the headline: how much extra cold-fetch cost the second
        # replica adds WITH the fleet plane (1.0 = none)
        "cold_fetch_amplification": round(
            r2f["fetches_per_key"] / max(r1["fetches_per_key"], 1e-9), 3
        ),
        "cache_merged": p_b.cache_merged + p_a.cache_merged,
    }


_ATTR_LABELS_REGO = """package attrlabels

violation[{"msg": msg}] {
    required := {key | key := input.parameters.labels[_]}
    provided := {key | input.review.object.metadata.labels[key]}
    missing := required - provided
    count(missing) > 0
    msg := sprintf("missing: %v", [missing])
}
"""

_ATTR_REPOS_REGO = """package attrrepos

violation[{"msg": msg}] {
    image := input.review.object.spec.containers[_].image
    not startswith(image, input.parameters.repo)
    msg := sprintf("image outside allowed repo: %v", [image])
}
"""


def build_attribution_client(driver, n_constraints, n_dead=0):
    """Self-contained policy load for the --attribution lane (no
    reference-library dependency): three templates of DIFFERENT static
    cost — a one-clause privileged check, a set-difference label check,
    and a per-container repo prefix check — cycled across n
    constraints, so the cost table has real weight variation to rank."""
    from gatekeeper_tpu.constraint import Backend, K8sValidationTarget

    client = Backend(driver).new_client(K8sValidationTarget())
    mix = (
        ("AttrPrivileged",
         _CHAOS_REGO.replace("chaosbench", "attrprivileged"), None),
        ("AttrLabels", _ATTR_LABELS_REGO, {"labels": ["app", "owner"]}),
        ("AttrRepos", _ATTR_REPOS_REGO, {"repo": "nginx"}),
    )
    for kind, rego, _params in mix:
        client.add_template({
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": kind.lower()},
            "spec": {
                "crd": {"spec": {"names": {"kind": kind}}},
                "targets": [{"target": TARGET, "rego": rego}],
            },
        })
    for i in range(n_constraints):
        kind, _rego, params = mix[i % len(mix)]
        # namespace affinity aligned with make_request's ns{i % 11}
        # (same scoping as build_webhook_client): gives the locality
        # planner real structure to co-locate, so the lane measures
        # pruned dispatch with falling dispatch_efficiency instead of
        # an unprunable all-match corpus
        spec = {"match": {
            "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
            "namespaces": [f"ns{i % 11}"],
        }}
        if params is not None:
            spec["parameters"] = params
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind,
            "metadata": {"name": f"a{i:04d}"},
            "spec": spec,
        })
    # provably-dead rows for the static-pruning lane: namespaces fully
    # excluded (corpus dead-match proof, GK-C006) with no
    # namespaceSelector, so the corpus pass marks them prunable and
    # the planner drops the rows before partitioning —
    # rows_excluded_static in the rung must equal n_dead
    for i in range(n_dead):
        kind, _rego, params = mix[i % len(mix)]
        spec = {"match": {
            "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
            "scope": "Namespaced",
            "namespaces": ["ns-dead"],
            "excludedNamespaces": ["ns-dead"],
        }}
        if params is not None:
            spec["parameters"] = params
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind,
            "metadata": {"name": f"dead{i:02d}"},
            "spec": spec,
        })
    return client


def _device_seconds_total(metrics):
    """Sum of driver_phase_seconds{phase=device_dispatch} across label
    sets — the measured device-execute total the attribution sums
    check compares against."""
    total = 0.0
    for key, d in metrics.snapshot()["distributions"].items():
        if key.startswith("driver_phase_seconds") and (
            'phase="device_dispatch"' in key
        ):
            total += float(d["sum"])
    return total


def _dispatch_rows(metrics):
    """(dispatched, total) constraint-row sums across partitions from
    the decision plane's pruning-efficiency counters —
    dispatch_efficiency = dispatched/total is ROADMAP item 1's
    headline instrument (falling with constraint count = pruning is
    working)."""
    dispatched = total = 0.0
    for key, v in metrics.snapshot()["counters"].items():
        if key.startswith("dispatch_rows_dispatched_total"):
            dispatched += float(v)
        elif key.startswith("dispatch_rows_total"):
            total += float(v)
    return dispatched, total


def run_attribution_bench(rungs=(10, 50, 200), n_requests=1200, k=4,
                          profile=False, err=sys.stderr):
    """The `--attribution` lane (docs/observability.md §Cost
    attribution): run the constraint ladder through the partitioned
    micro-batching handler with the CostAttributor wired, and report
    per rung (a) the top-10 costliest constraints — item 1's pruning
    target list — and (b) the sums check: attributed per-constraint
    device seconds vs the measured device-execute total (must agree
    within 10%; the model changes WHO is charged, never HOW MUCH).
    `--profile` additionally captures a JAX/XPlane device profile
    DURING the largest rung's measured replay."""
    from gatekeeper_tpu.analysis.corpus import CorpusPlane
    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.control.runner import capture_jax_profile
    from gatekeeper_tpu.metrics import MetricsRegistry
    from gatekeeper_tpu.obs import CostAttributor, DecisionLog, Tracer
    from gatekeeper_tpu.parallel.partition import PartitionDispatcher
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )

    out = []
    prof = None
    overhead = None
    for n_con in rungs:
        metrics = MetricsRegistry()
        driver = TpuDriver()
        driver.set_metrics(metrics)
        attributor = CostAttributor(metrics=metrics)
        driver.set_attributor(attributor)
        client = build_attribution_client(driver, n_con, n_dead=3)
        # corpus plane: the verdict-safe static-pruning input — the
        # seeded dead rows are proved dead once, synchronously, before
        # the measured replays (production recomputes on churn; the
        # bench corpus is static after load)
        corpus_plane = CorpusPlane(client, metrics=metrics,
                                   debounce_s=0.0)
        corpus_report = corpus_plane.refresh()
        # tracing is always-on in production and the decision plane
        # joins its dispatch facts by trace id — both ride every
        # measured rung (the ≤5% p50 overhead budget is measured below
        # as an off/on phase pair with the tracer on throughout)
        tracer = Tracer(max_traces=2048)
        decisions = DecisionLog(metrics=metrics, max_per_s=0)
        # partition count scales with the corpus (floor k): bigger
        # corpora split finer, so the locality planner can isolate each
        # namespace group and mask-gated pruning drives
        # dispatch_efficiency DOWN as constraint count grows — the
        # inverse of the pre-pruning flat-1.0 ladder
        k_rung = min(n_con, max(k, n_con // 8), 64)
        disp = PartitionDispatcher(
            client, TARGET, k=k_rung, metrics=metrics,
            tracer=tracer, attributor=attributor, corpus=corpus_plane,
        )
        batcher = MicroBatcher(
            client, TARGET, window_ms=2.0, metrics=metrics,
            partitioner=disp, decisions=decisions, tracer=tracer,
        )
        handler = BatchedValidationHandler(
            batcher, request_timeout=60, decision_log=decisions,
            tracer=tracer,
        )
        batcher.start()
        try:
            _warm_route(client)
            replay(handler, [make_request(i) for i in range(256)], 64)
            replay(handler, [make_request(i) for i in range(512)], 128)
            if n_con == max(rungs):
                # decision-plane overhead at the largest rung: the same
                # replay with the plane detached, then reattached — the
                # acceptance budget is ≤5% on p50
                batcher.decisions = None
                handler.decision_log = None
                n_off = max(400, n_requests // 3)
                r_off = replay(
                    handler,
                    [make_request(i) for i in range(n_off)], 128,
                )
                batcher.decisions = decisions
                handler.decision_log = decisions
                overhead = {"constraints": n_con, "off": r_off}
            attributor.reset()
            dev0 = _device_seconds_total(metrics)
            rows0 = _dispatch_rows(metrics)
            capture = []
            if profile and n_con == max(rungs):
                # one XPlane capture riding the measured replay: the
                # profile shows the fused dispatch under REAL load, not
                # an idle device (the --enable-pprof endpoint's bench
                # counterpart; single rung, single capture)
                th = ThreadPoolExecutor(max_workers=1)
                fut = th.submit(capture_jax_profile, 2.0)
                capture.append((th, fut))
            n_sub = max(400, n_requests // 3)
            r = replay(
                handler, [make_request(i) for i in range(n_sub)], 128
            )
            for th, fut in capture:
                prof = fut.result(timeout=90)
                th.shutdown(wait=False)
            measured = _device_seconds_total(metrics) - dev0
            attributed = attributor.snapshot()["total_device_seconds"]
            rows1 = _dispatch_rows(metrics)
            rows_dispatched = rows1[0] - rows0[0]
            rows_total = rows1[1] - rows0[1]
            top = attributor.top(10)
            sums_ok = bool(
                measured > 0
                and abs(attributed - measured) <= 0.10 * measured
            )
            touched = disp.touched_stats()
            plan_now = disp.plan()
            rows_excluded = len(
                getattr(plan_now, "excluded_static", ()) or ()
            )
            rung = {
                "constraints": n_con,
                "partitions": k_rung,
                # pruning telemetry: of the plan's partitions, how many
                # a batch actually dispatched to (p50/max over the
                # rung's replays)
                "partitions_touched_p50": touched.get("p50"),
                "partitions_touched_max": touched.get("max"),
                "replay": {
                    key: r[key]
                    for key in ("requests", "throughput_rps",
                                "p50_ms", "p99_ms")
                },
                "measured_device_seconds": round(measured, 6),
                "attributed_device_seconds": round(attributed, 6),
                "attribution_ratio": (
                    round(attributed / measured, 4) if measured else None
                ),
                "sums_ok": sums_ok,
                # the pruning-efficiency headline (ROADMAP item 1):
                # constraint-rows dispatched / total over the measured
                # replay — falling with constraint count is what batch-
                # aware pruned dispatch will be judged by
                "rows_dispatched": int(rows_dispatched),
                "rows_total": int(rows_total),
                "dispatch_efficiency": (
                    round(rows_dispatched / rows_total, 4)
                    if rows_total else None
                ),
                # verdict-safe static pruning (corpus pass): provably-
                # dead rows the planner excluded before partitioning,
                # and the corpus diagnostic count backing the proof
                "rows_excluded_static": rows_excluded,
                "corpus_diagnostics": sum(
                    (corpus_report.counts() or {}).values()
                ),
                "decisions": decisions.snapshot(),
                "top_costs": top,
            }
            if overhead is not None and overhead.get(
                "constraints"
            ) == n_con and "on" not in overhead:
                overhead["on"] = {
                    key: r[key] for key in ("p50_ms", "p99_ms")
                }
                p_off = overhead["off"]["p50_ms"]
                overhead["p50_overhead_frac"] = (
                    round(r["p50_ms"] / p_off - 1.0, 4) if p_off else None
                )
                rung["decision_overhead"] = overhead
            out.append(rung)
            top3 = [f"{t['kind']}/{t['name']}" for t in top[:3]]
            print(
                f"attribution rung c={n_con}: measured="
                f"{measured:.4f}s attributed={attributed:.4f}s "
                f"sums_ok={sums_ok} "
                f"dispatch_efficiency={rung['dispatch_efficiency']} "
                f"rows_excluded_static={rows_excluded} "
                f"top={top3}",
                file=err,
            )
        finally:
            batcher.stop()
            disp.close()
    return {"rungs": out, "profile": prof, "decision_overhead": overhead}


# the reference harness's constraint-count ladder
# (pkg/webhook/policy_benchmark_test.go:265-276)
LADDER = (5, 10, 50, 100, 200, 1000, 2000)


def run_constraint_ladder(err=sys.stderr, rungs=LADDER, budget_s=None,
                          profile=False):
    """Latency-vs-policy-count curve (VERDICT r4 #3): p50/p99/rps per
    constraint-count rung for all three serving paths — the serial
    Python-interpreter handler (the reference's architecture, measured
    serially like the Go b.N loop), the fused micro-batching handler
    (c=128), and the native C++ bridge stack (c=128). 100%-violating
    requests, the reference harness's stress shape.

    budget_s bounds total wall time: rungs run SMALL, MID, LARGE first
    (a truncated run still spans the curve, and the first two samples
    feed an affine fixed+marginal cost fit before the big rung), then
    alternating fill. A rung is deferred when the fit's 1.5x-padded
    estimate exceeds the remaining budget, and deferred rungs are
    re-evaluated on later passes as samples sharpen the fit — an
    overrun must degrade the curve, not erase the whole artifact (the
    r4 lesson applied to time)."""
    from gatekeeper_tpu.constraint import RegoDriver, TpuDriver
    from gatekeeper_tpu.webhook import ValidationHandler
    from gatekeeper_tpu.webhook.bridge import BridgeStack, build_frontend
    from gatekeeper_tpu.webhook.server import (
        BatchedValidationHandler,
        MicroBatcher,
    )
    import json as _json
    import tempfile
    import urllib.request

    have_bridge = build_frontend() is not None
    # small, mid, large first (curve coverage under truncation AND two
    # spread samples for the affine cost model before the big rung),
    # then alternating fill
    remaining = sorted(rungs)
    order: list = []
    if len(remaining) >= 3:
        order.append(remaining.pop(0))
        # true midpoint EXCLUDING the max: with 3 rungs this must pick
        # the middle one, not the largest, or the affine fit gets no
        # second spread sample before the big rung
        order.append(remaining.pop((len(remaining) - 1) // 2))
        order.append(remaining.pop(-1))
    while remaining:
        order.append(remaining.pop(0))
        if remaining:
            order.append(remaining.pop(-1))
    t_start = time.perf_counter()
    samples: list = []  # (n_constraints, wall_seconds)

    def estimate(n_next: float) -> float:
        """Predicted rung wall: affine in constraint count once two
        spread samples exist. A pure count-ratio scale from the
        cheapest rung books its FIXED overhead (client build, warmup,
        replay floor) as marginal cost and over-skips the big rungs by
        ~10x; the affine fit separates the two."""
        lo = min(samples)
        hi = max(samples)
        if hi[0] > lo[0]:
            marginal = max(0.0, (hi[1] - lo[1]) / (hi[0] - lo[0]))
            fixed = max(0.0, lo[1] - marginal * lo[0])
            est = fixed + marginal * n_next
        else:
            # one sample: its wall is mostly fixed overhead, so a raw
            # count-ratio scale over-skips the calibration (mid) rung;
            # cap the ratio effect — worst case we overspend one
            # bounded rung and every later estimate has real data
            est = hi[1] * min(n_next / hi[0], 4.0)
        # never cheaper than a smaller rung already measured
        return max(est, hi[1] if n_next >= hi[0] else lo[1]) * 1.5

    out = []
    queue = list(order)
    progress = True
    while queue and progress:
        progress = False
        deferred = []
        for n_con in queue:
            if budget_s is not None:
                elapsed = time.perf_counter() - t_start
                fits = (
                    budget_s >= 30
                    if not samples
                    else elapsed + estimate(n_con) <= budget_s
                )
                if not fits:
                    # re-evaluated next pass: early estimates (one
                    # sample) are crude; later samples sharpen the
                    # affine fit and may admit this rung after all
                    deferred.append(n_con)
                    continue
            progress = True
            t_rung = time.perf_counter()
            rung = {"constraints": n_con}

            # interpreter path, serial (subsample scaled: per-request cost
            # grows with the rung)
            cpu_n = max(25, min(200, 20_000 // n_con))
            cpu_handler = ValidationHandler(
                build_webhook_client(RegoDriver(), n_con), TARGET
            )
            reqs = [make_request(i) for i in range(cpu_n)]
            cpu_handler.handle(reqs[0])  # warm
            t0 = time.perf_counter()
            lat = np.zeros(cpu_n)
            for i, r in enumerate(reqs):
                t1 = time.perf_counter()
                cpu_handler.handle(r)
                lat[i] = time.perf_counter() - t1
            wall = time.perf_counter() - t0
            rung["interp"] = {
                "requests": cpu_n,
                "throughput_rps": round(cpu_n / wall, 1),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            }

            # fused micro-batching path, c=128 — partitioned with the
            # cost/locality planner so mask-gated pruning is ON (the
            # default fast path): each batch dispatches only the
            # partitions its namespaces touch
            from gatekeeper_tpu.parallel.partition import (
                PartitionDispatcher,
            )

            client = build_webhook_client(TpuDriver(), n_con)
            ladder_disp = PartitionDispatcher(
                client, TARGET, k=min(n_con, max(4, n_con // 8), 64),
            )
            batcher = MicroBatcher(
                client, TARGET, window_ms=2.0, partitioner=ladder_disp,
            )
            handler = BatchedValidationHandler(batcher, request_timeout=60)
            batcher.start()
            try:
                _warm_route(client)
                replay(handler, [make_request(i) for i in range(512)], 128)
                capture = None
                if profile and not any("profile" in r for r in out):
                    # --profile: one JAX/XPlane capture riding THIS
                    # rung's measured fused replay — a device profile
                    # under real load, not an idle trace (the
                    # /debug/profile endpoint's ladder counterpart)
                    from gatekeeper_tpu.control.runner import (
                        capture_jax_profile,
                    )

                    _pex = ThreadPoolExecutor(max_workers=1)
                    capture = (_pex, _pex.submit(capture_jax_profile, 2.0))
                n_sub = 1500
                r = replay(handler, [make_request(i) for i in range(n_sub)], 128)
                rung["fused"] = {
                    k: r[k]
                    for k in ("requests", "throughput_rps", "p50_ms", "p99_ms")
                }
                rung["fused"]["partitions_touched"] = (
                    ladder_disp.touched_stats()
                )
                # IR feature-liveness headline: dead token slots the
                # encoder dropped before padding across this rung's
                # batches (0 = masking off or nothing provable)
                _drv = getattr(client, "_driver", None)
                rung["fused"]["columns_skipped_static"] = int(
                    getattr(_drv, "columns_skipped_static", 0) or 0
                )
                if capture is not None:
                    _pex, fut = capture
                    rung["profile"] = fut.result(timeout=90)
                    _pex.shutdown(wait=False)
                    print(
                        f"ladder profile captured: {rung['profile']}",
                        file=err,
                    )
            finally:
                batcher.stop()
                ladder_disp.close()

            # native bridge stack, c=128 full HTTP
            if have_bridge:
                bclient = build_webhook_client(TpuDriver(), n_con)
                _warm_route(bclient)
                sock = tempfile.mktemp(prefix="gk-lad-", suffix=".sock")
                stack = BridgeStack(
                    bclient, TARGET, sock, deadline_ms=60_000,
                    request_timeout=60,
                )
                stack.start()
                try:
                    def post(i):
                        body = _json.dumps(
                            {
                                "apiVersion": "admission.k8s.io/v1",
                                "kind": "AdmissionReview",
                                "request": make_request(i),
                            }
                        ).encode()
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{stack.port}/v1/admit",
                            data=body,
                            headers={"Content-Type": "application/json"},
                            method="POST",
                        )
                        t1 = time.perf_counter()
                        with urllib.request.urlopen(req, timeout=120) as resp:
                            resp.read()
                        return time.perf_counter() - t1

                    with ThreadPoolExecutor(max_workers=128) as ex:
                        list(ex.map(post, range(512)))  # warm
                    n_sub = 1500
                    blat = np.zeros(n_sub)

                    def one(i):
                        blat[i] = post(i)

                    t0 = time.perf_counter()
                    with ThreadPoolExecutor(max_workers=128) as ex:
                        list(ex.map(one, range(n_sub)))
                    wall = time.perf_counter() - t0
                    rung["bridge"] = {
                        "requests": n_sub,
                        "throughput_rps": round(n_sub / wall, 1),
                        "p50_ms": round(float(np.percentile(blat, 50)) * 1e3, 2),
                        "p99_ms": round(float(np.percentile(blat, 99)) * 1e3, 2),
                    }
                finally:
                    stack.stop()
            else:
                rung["bridge"] = {"skipped": "no C++ toolchain"}
            wall = time.perf_counter() - t_rung
            samples.append((n_con, wall))
            rung["wall_seconds"] = round(wall, 1)
            print(f"constraint ladder rung: {rung}", file=err)
            out.append(rung)
        queue = deferred
    truncated = queue
    if truncated:
        print(
            f"constraint ladder truncated by time budget; skipped rungs "
            f"{sorted(truncated)}",
            file=err,
        )
    # rows stay homogeneous (BENCH_r* consumers index r["constraints"]);
    # truncation is reported out-of-band
    return sorted(out, key=lambda r: r["constraints"]), sorted(truncated)


def run_bridge_bench(n_requests, n_constraints, err=sys.stderr):
    """The native serving stack (C++ front + unix-socket batch backend):
    full-HTTP replay through the compiled bridge_frontend binary at high
    concurrency — the no-GIL-on-the-accept-path architecture SURVEY §7
    step 5 names. Skipped (with a marker) when no C++ toolchain."""
    import json as _json
    import tempfile
    import urllib.request

    from gatekeeper_tpu.constraint import TpuDriver
    from gatekeeper_tpu.webhook.bridge import BridgeStack, build_frontend

    if build_frontend() is None:
        return {"skipped": "no C++ toolchain"}
    client = build_webhook_client(TpuDriver(), n_constraints)
    _warm_route(client)
    sock = tempfile.mktemp(prefix="gk-bridge-", suffix=".sock")
    stack = BridgeStack(
        client, TARGET, sock, deadline_ms=60_000, request_timeout=60
    )
    stack.start()
    out = []
    try:
        def post(i, violating):
            body = _json.dumps(
                {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": make_request(i, violating=violating),
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{stack.port}/v1/admit",
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=120) as resp:
                doc = _json.loads(resp.read())
            return time.perf_counter() - t0, doc["response"]["allowed"]

        # warm the batch-size buckets both profiles produce at full
        # concurrency (compiles inside the measurement skew p99)
        for viol in (True, False):
            with ThreadPoolExecutor(max_workers=128) as ex:
                list(ex.map(lambda i: post(i, viol), range(512)))
        for violating in (True, False):
            n_sub = max(1000, n_requests // 8)
            lat = np.zeros(n_sub)
            allowed_arr = np.zeros(n_sub, bool)  # per-index: no shared
            # counter races across the 128 workers

            def one(i):
                dt, allowed = post(i, violating)
                lat[i] = dt
                allowed_arr[i] = allowed

            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=128) as ex:
                list(ex.map(one, range(n_sub)))
            wall = time.perf_counter() - t0
            r = {
                "concurrency": 128,
                "requests": n_sub,
                "violating": violating,
                "wall_seconds": round(wall, 3),
                "throughput_rps": round(n_sub / wall, 1),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
                "denied": int((~allowed_arr).sum()),
            }
            out.append(r)
            print(f"bridge replay: {r}", file=err)
    finally:
        stack.stop()
    return out


def _summarize(mode, res):
    """One short driver-parseable line with the headline numbers: the
    full JSON line has outgrown capture buffers before (BENCH_r05's
    parsed: null), so the compact SUMMARY survives truncation. The
    schema is the shared per-mode contract in gatekeeper_tpu/summary.py
    (tests/test_summary_contract.py round-trips every mode through the
    strict reader)."""
    from gatekeeper_tpu.summary import REQUIRED_FIELDS, format_summary

    head = {}
    try:
        if mode == "webhook":
            row = next(
                (r for r in res.get("tpu_batched", [])
                 if r.get("violating") and r.get("concurrency") == 8),
                None,
            ) or (res.get("tpu_batched") or [{}])[0]
            head.update(
                p50_ms=row.get("p50_ms"), p99_ms=row.get("p99_ms"),
                throughput_rps=row.get("throughput_rps"),
            )
        elif mode == "ladder":
            rungs = res.get("rungs") or []
            head.update(
                rungs=len(rungs), skipped=res.get("skipped"),
                last=rungs[-1] if rungs else None,
            )
            prof = next(
                (r["profile"] for r in rungs if r.get("profile")), None
            )
            if prof:
                head["profile_trace_dir"] = prof.get("trace_dir")
        elif mode == "attribution":
            rungs = res.get("rungs") or []
            head["rungs"] = len(rungs)
            head["sums_ok"] = all(r.get("sums_ok") for r in rungs)
            # per-rung pruning efficiency (ROADMAP item 1's gauge):
            # dispatched/total constraint rows at every rung
            head["dispatch_efficiency"] = {
                str(r["constraints"]): r.get("dispatch_efficiency")
                for r in rungs
            }
            # partition touch counts per rung (the pruning width gauge
            # next to the efficiency depth gauge)
            head["partitions_touched_p50"] = {
                str(r["constraints"]): r.get("partitions_touched_p50")
                for r in rungs
            }
            head["partitions_touched_max"] = {
                str(r["constraints"]): r.get("partitions_touched_max")
                for r in rungs
            }
            # verdict-safe static pruning per rung: dead rows the
            # planner dropped (down = regression: the corpus pass
            # stopped proving the seeded dead rows) and the corpus
            # diagnostic count (up = new corpus findings)
            head["rows_excluded_static"] = {
                str(r["constraints"]): r.get("rows_excluded_static")
                for r in rungs
            }
            head["corpus_diagnostics"] = {
                str(r["constraints"]): r.get("corpus_diagnostics")
                for r in rungs
            }
            if rungs:
                last = max(rungs, key=lambda r: r["constraints"])
                head["constraints"] = last["constraints"]
                head["attribution_ratio"] = last.get("attribution_ratio")
                # the acceptance headline: the top-10 costliest
                # constraints at the largest rung — item 1's target list
                head["top10"] = [
                    f"{t['kind']}/{t['name']}"
                    for t in (last.get("top_costs") or [])[:10]
                ]
            oh = res.get("decision_overhead")
            if oh:
                head["decision_overhead_p50_frac"] = oh.get(
                    "p50_overhead_frac"
                )
            prof = res.get("profile")
            if prof:
                head["profile_trace_dir"] = prof.get("trace_dir")
        elif mode == "churn":
            waves = res.get("waves") or []
            head["waves"] = len(waves)
            if waves:
                head["wave"] = waves[-1].get("wave")
            head["ingest_to_serve_ms"] = res.get("ingest_to_serve_ms")
            head["degraded_dispatches"] = res.get("degraded_dispatches")
            head["http_5xx"] = res.get("http_5xx")
            head["compiles"] = res.get("compiles")
            head["swaps"] = res.get("swaps")
        elif mode == "slo":
            head["phases"] = len(res.get("phases") or [])
            for k in ("slo_attainment", "saturation", "burn_rate_fast",
                      "headroom_rps", "breaches", "burning",
                      "error_budget_remaining"):
                if k in res:
                    head[k] = res[k]
        elif mode == "integrity":
            head["phases"] = len(res.get("phases") or [])
            for k in ("divergence_rate", "canary_overhead_frac",
                      "detection_latency_s", "selftest_healed",
                      "shadow_sampled"):
                if k in res:
                    head[k] = res[k]
        elif mode == "sched":
            head["phases"] = len(res.get("phases") or [])
            for k in ("quiet_p50_ms", "quiet_p99_ms", "noisy_p50_ms",
                      "noisy_p99_ms", "quiet_attainment",
                      "noisy_attainment", "tenant_attainment_min",
                      "predicted_miss_shed", "blind_shed"):
                if k in res:
                    head[k] = res[k]
        elif mode == "ingest":
            head["phases"] = len(res.get("phases") or [])
            for k in ("offered_rps", "rps_sustained", "framed_vs_http1",
                      "http1_rps_sustained", "keepalive_rps_sustained",
                      "framed_attainment", "http1_attainment",
                      "p50_ms", "p99_ms", "decode_p50_ms",
                      "decode_span_share", "conns_per_1k_framed",
                      "conns_per_1k_http1"):
                if k in res:
                    head[k] = res[k]
        elif mode == "mutate":
            replays = res.get("replays") or []
            if replays:
                last = replays[-1]
                for k in ("p50_ms", "p99_ms", "throughput_rps",
                          "batch_occupancy"):
                    if k in last:
                        head[k] = last[k]
        elif isinstance(res, dict):
            phases = res.get("phases")
            if isinstance(phases, list) and phases:
                head["phases"] = len(phases)
                last = phases[-1]
                for k in ("phase", "p50_ms", "p99_ms", "throughput_rps",
                          "shed_rate", "cache_hit_rate",
                          "fetches_per_batch"):
                    if k in last:
                        head[k] = last[k]
            for k in ("p50_ms", "p99_ms", "throughput_rps", "shed_rate",
                      "hit_rate", "fetches_per_batch",
                      "fetches_per_key_n1", "fetches_per_key_n2_isolated",
                      "fetches_per_key_n2_fleet",
                      "cold_fetch_amplification",
                      "partitions", "parity_ok",
                      "healthy_subset_degraded",
                      "degraded_coverage_fraction", "recovery_s",
                      "home_restored"):
                if k in res:
                    head[k] = res[k]
    except Exception as e:  # the summary must never kill the artifact
        head["error"] = str(e)
    # the contract guarantee: every required headline key is PRESENT
    # (null when a truncated/failed run could not measure it) — the
    # strict reader keys on presence, not truthiness
    for f in REQUIRED_FIELDS.get(mode, ()):
        head.setdefault(f, None)
    return format_summary(mode, head)


def run_soak_bench(argv, err=sys.stderr):
    """The `--soak` lane (docs/operations.md §Soak runbook): minutes of
    open-loop Poisson load with a declarative churn/fault/kill timeline,
    reported as SLO attainment, shed rate, breaker transitions, the
    device-time split, a capacity model, and leak evidence.

        python bench_webhook.py --soak                    # full default
        python bench_webhook.py --soak --smoke            # ~10 s smoke
        python bench_webhook.py --soak --scenario f.json  # custom
        python bench_webhook.py --soak 120 80             # duration rps
    """
    from gatekeeper_tpu.soak import (
        default_scenario,
        load_scenario,
        run_soak,
        smoke_scenario,
    )

    if "--scenario" in argv:
        path = argv[argv.index("--scenario") + 1]
        scn = load_scenario(path)
    elif "--smoke" in argv:
        scn = smoke_scenario()
    else:
        scn = default_scenario()
        pos = [a for a in argv[1:] if not a.startswith("--")]
        if pos:
            scn.duration_s = float(pos[0])
        if len(pos) > 1:
            scn.rps = float(pos[1])
        scn.validate()
    print(f"soak scenario: {scn.name} duration={scn.duration_s}s "
          f"rps={scn.rps} replicas={scn.replicas}", file=err)
    return run_soak(scn, err=err)


if __name__ == "__main__":
    import json

    if "--soak" in sys.argv:
        from gatekeeper_tpu.soak import summarize_soak

        res = run_soak_bench(sys.argv)
        print(json.dumps(res))
        print(summarize_soak(res))
    elif "--ladder" in sys.argv:
        rows, skipped = run_constraint_ladder(
            profile="--profile" in sys.argv
        )
        res = {"rungs": rows, "skipped": skipped}
        print(json.dumps(res))
        print(_summarize("ladder", res))
    elif "--attribution" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 1_200
        rungs = (
            tuple(int(x) for x in pos[1].split(","))
            if len(pos) > 1
            else (10, 50, 200)
        )
        res = run_attribution_bench(
            rungs=rungs, n_requests=n_req,
            profile="--profile" in sys.argv,
        )
        print(json.dumps(res))
        print(_summarize("attribution", res))
    elif "--chaos" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 3_000
        n_con = int(pos[1]) if len(pos) > 1 else 20
        res = run_chaos_bench(n_req, n_con)
        print(json.dumps(res))
        print(_summarize("chaos", res))
    elif "--partitions" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 2_000
        n_con = int(pos[1]) if len(pos) > 1 else 40
        n_parts = int(pos[2]) if len(pos) > 2 else 4
        res = run_partitions_bench(n_req, n_con, n_parts)
        print(json.dumps(res))
        print(_summarize("partitions", res))
    elif "--churn" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 600
        sizes = (
            tuple(int(x) for x in pos[1].split(","))
            if len(pos) > 1
            else (10, 50, 500)
        )
        n_parts = int(pos[2]) if len(pos) > 2 else 4
        res = run_churn_bench(n_req, sizes, n_parts)
        print(json.dumps(res))
        print(_summarize("churn", res))
    elif "--external" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 3_000
        n_keys = int(pos[1]) if len(pos) > 1 else 7
        res = run_external_bench(n_req, n_keys)
        print(json.dumps(res))
        print(_summarize("external", res))
    elif "--fleet" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 1_200
        n_keys = int(pos[1]) if len(pos) > 1 else 24
        res = run_fleet_bench(n_req, n_keys)
        print(json.dumps(res))
        print(_summarize("fleet", res))
    elif "--mutate" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 10_000
        n_mut = int(pos[1]) if len(pos) > 1 else 30
        res = run_mutate_bench(n_req, n_mut)
        print(json.dumps(res))
        print(_summarize("mutate", res))
    elif "--slo" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 1_800
        n_con = int(pos[1]) if len(pos) > 1 else 20
        res = run_slo_bench(n_req, n_con)
        print(json.dumps(res))
        print(_summarize("slo", res))
    elif "--integrity" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        n_req = int(pos[0]) if pos else 1_800
        n_con = int(pos[1]) if len(pos) > 1 else 20
        k = int(pos[2]) if len(pos) > 2 else 3
        res = run_integrity_bench(n_req, n_con, k)
        print(json.dumps(res))
        print(_summarize("integrity", res))
    elif "--sched" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        dur = float(pos[0]) if pos else 6.0
        rps = float(pos[1]) if len(pos) > 1 else 600.0
        res = run_sched_bench(dur, rps)
        print(json.dumps(res))
        print(_summarize("sched", res))
    elif "--ingest" in sys.argv:
        pos = [a for a in sys.argv[1:] if not a.startswith("--")]
        dur = float(pos[0]) if pos else 6.0
        rate = float(pos[1]) if len(pos) > 1 else 1500.0
        res = run_ingest_bench(dur, rate)
        print(json.dumps(res))
        print(_summarize("ingest", res))
    else:
        n_req = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
        n_con = int(sys.argv[2]) if len(sys.argv) > 2 else 50
        res = run_webhook_bench(n_req, n_con)
        print(json.dumps(res))
        print(_summarize("webhook", res))
